"""`make validate` tail: a CLI-shaped smoke on a synthetic corpus with the
jax backend's report byte-compared against the Python oracle's, plus the
observability smoke (`make trace-smoke` / --trace-smoke): a traced
two-family pipeline run whose emitted Chrome-trace JSON must parse and
contain the three span categories the obs contract promises — nested
pipeline-phase spans, a render-worker span from a child process, and RPC
client+server spans sharing one propagated trace id.

Also the analysis-route gate (ISSUE 3): a forced NEMO_ANALYSIS_IMPL=sparse
pipeline must byte-reproduce the forced-dense report end to end, and each
routed run must record an analysis.route metric for every verb the smoke
dispatches (fused + diff) — the CI assertion that the crossover's routes
both exist and agree.

Covers the figure-render pipeline end to end (report/render.py) with an
all-figures smoke: the production report renders every figure
(figures="all") through the deduplicated / cached / parallel scheduler and
must be byte-identical — every .dot, every .svg, debugging.json — to the
same backend rendering sequentially (explicit Reporter, no scheduler: the
oracle render path).  A second pass must then serve every unique figure
from the persistent SVG cache (zero renders) and still match.  Backend
analysis parity stays what it was: the jax debugging.json equals the
Python oracle backend's (figure node ORDER differs across backends by
construction, so figure files are only byte-compared within one backend).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _tree(root: str) -> dict[str, bytes]:
    from nemo_tpu.analysis.pipeline import report_tree_bytes

    return report_tree_bytes(root)


def _validate_trace_events(doc: dict) -> list[dict]:
    """Structural Chrome-trace-event validation; returns the event list."""
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        raise ValueError("not a Chrome-trace JSON object (no traceEvents array)")
    events = doc["traceEvents"]
    if not events:
        raise ValueError("trace has no events")
    for ev in events:
        if ev.get("ph") not in ("X", "M"):
            raise ValueError(f"unexpected event phase {ev.get('ph')!r}")
        for k in ("name", "pid", "tid"):
            if k not in ev:
                raise ValueError(f"event missing {k!r}: {ev}")
        if ev["ph"] == "X" and not (
            isinstance(ev.get("ts"), int) and isinstance(ev.get("dur"), int)
        ):
            raise ValueError(f"complete event without int ts/dur: {ev}")
    return events


def trace_smoke() -> int:
    """Run a tiny traced pipeline over TWO case-study families (overlapped
    driver, 2-worker render pool) plus one RPC against a sidecar
    SUBPROCESS, then validate the emitted trace file.

    The RPC leg needs grpcio; like the service tests (importorskip), it is
    skipped — loudly — where grpc is absent, and the pipeline/worker-span
    validation still runs."""
    import importlib.util
    import subprocess
    import sys as _sys

    from nemo_tpu.obs import trace as obs_trace
    from nemo_tpu.utils.jax_config import pin_platform

    have_grpc = importlib.util.find_spec("grpc") is not None
    pin_platform("cpu")
    with tempfile.TemporaryDirectory(prefix="nemo_trace_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        # The span assertions need the pipeline to actually run its phases
        # (and the smoke must not write into the user's results cache).
        os.environ["NEMO_RESULT_CACHE"] = "off"
        os.environ["NEMO_RENDER_WORKERS"] = "2"
        trace_path = os.path.join(tmp, "trace.json")
        t = obs_trace.start_trace(trace_path)
        tid = t.trace_id

        from nemo_tpu.analysis.pipeline import run_debug_dirs
        from nemo_tpu.backend.jax_backend import JaxBackend
        from nemo_tpu.models.case_studies import write_case_study

        dirs = [
            write_case_study(fam, n_runs=4, seed=7, out_dir=os.path.join(tmp, "corp"))
            for fam in ("pb_asynchronous", "ZK-1270-racing-sent-flag")
        ]
        run_debug_dirs(dirs, os.path.join(tmp, "results"), JaxBackend, figures="failed")

        # RPC spans against a REAL second process: spawn a CPU sidecar and
        # push one fused step through it (trace context propagates out via
        # gRPC metadata; the server's spans ride home in trailing metadata).
        if not have_grpc:
            print(
                "trace-smoke: grpcio not installed; skipping the sidecar RPC "
                "leg (pipeline + worker spans still validated)",
                file=sys.stderr,
            )
            return _check_trace(obs_trace.finish(), tid, expect_rpc=False)
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        sidecar_log = os.path.join(tmp, "sidecar.log")
        log_fh = open(sidecar_log, "w")
        proc = subprocess.Popen(
            [_sys.executable, "-m", "nemo_tpu.service.server",
             "--port", str(port), "--platform", "cpu"],
            stdout=log_fh,
            stderr=subprocess.STDOUT,
        )
        try:
            from nemo_tpu.ingest.molly import load_molly_output
            from nemo_tpu.models.pipeline_model import pack_molly_for_step
            from nemo_tpu.service.client import RemoteAnalyzer

            pre, post, static = pack_molly_for_step(load_molly_output(dirs[0]))
            # Wait for the LISTENING socket before creating the channel:
            # this environment's grpc wedges a channel whose first connect
            # raced the server's bind ("FD Shutdown" timeouts survive every
            # reconnect backoff), so the Health polling alone never recovers.
            import time as _time

            deadline = _time.monotonic() + 120.0
            while True:
                try:
                    socket.create_connection(("127.0.0.1", port), 2.0).close()
                    break
                except OSError:
                    if _time.monotonic() > deadline or proc.poll() is not None:
                        raise RuntimeError(
                            f"sidecar never listened on port {port} "
                            f"(rc={proc.poll()})"
                        )
                    _time.sleep(0.5)
            try:
                with RemoteAnalyzer(target=f"127.0.0.1:{port}") as client:
                    client.wait_ready(deadline=90.0)
                    client.analyze(pre, post, static)
                    health = client.health()
            except Exception:
                if os.path.exists(sidecar_log):
                    with open(sidecar_log, "r", encoding="utf-8") as fh:
                        print(
                            "trace-smoke: sidecar log tail:\n" + fh.read()[-3000:],
                            file=sys.stderr,
                        )
                raise
            if "metrics" not in health or "counters" not in health["metrics"]:
                print(
                    f"trace-smoke: health() carries no sidecar metrics snapshot: {health}",
                    file=sys.stderr,
                )
                return 1
            if not health["metrics"]["counters"].get("serve.analyze_chunks"):
                print(
                    "trace-smoke: sidecar metrics did not count the Analyze RPC: "
                    f"{health['metrics']['counters']}",
                    file=sys.stderr,
                )
                return 1
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                # A sidecar wedged in native/jax code can ignore SIGTERM;
                # the smoke must still report ITS result, not a cleanup
                # traceback, and must not orphan the process.
                proc.kill()
                proc.wait(timeout=15)
            log_fh.close()

        return _check_trace(obs_trace.finish(), tid, expect_rpc=True)


def _check_trace(out: str, tid: str, expect_rpc: bool) -> int:
    """Validate the emitted trace file's structure and span categories."""
    with open(out, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    try:
        events = _validate_trace_events(doc)
    except ValueError as ex:
        print(f"trace-smoke: invalid trace: {ex}", file=sys.stderr)
        return 1

    spans = [e for e in events if e["ph"] == "X"]
    me = os.getpid()

    def named(prefix):
        return [e for e in spans if e["name"].startswith(prefix)]

    phases = named("phase:")
    kernels = named("kernel:")
    nested = any(
        p["pid"] == k["pid"] and p["tid"] == k["tid"]
        and p["ts"] <= k["ts"] and k["ts"] + k["dur"] <= p["ts"] + p["dur"]
        for k in kernels
        for p in phases
    )
    worker = [e for e in named("render:svg") if e["pid"] != me]
    rpc = [
        e for e in named("rpc:")
        if (e.get("args") or {}).get("trace_id") == tid
    ]
    serve = [
        e for e in named("serve:")
        if (e.get("args") or {}).get("trace_id") == tid and e["pid"] != me
    ]
    problems = []
    distinct_phases = {e["name"] for e in phases}
    if len(distinct_phases) < 3:
        problems.append(
            f"expected >=3 distinct phase names, got {len(distinct_phases)} "
            f"across {len(phases)} phase spans"
        )
    if not nested:
        problems.append("no kernel span nested inside a phase span")
    if not worker:
        problems.append("no render-worker span from a child process")
    if expect_rpc and not rpc:
        problems.append("no client rpc span carrying the trace id")
    if expect_rpc and not serve:
        problems.append("no sidecar serve span sharing the propagated trace id")
    if problems:
        print("trace-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        f"trace-smoke: ok — {len(spans)} spans across "
        f"{len({e['pid'] for e in spans})} processes "
        f"({len(phases)} phase, {len(kernels)} kernel, {len(worker)} "
        f"worker, {len(rpc)} rpc, {len(serve)} sidecar), trace id {tid}"
    )
    return 0


def obs_smoke() -> int:
    """Operational-observability smoke (`make obs-smoke`, also the tail of
    `make validate`): boot a sidecar SUBPROCESS with `--metrics-port`,
    drive a real RPC workload through it (a tiny traced pipeline on the
    ServiceBackend, so Kernel RPCs dispatch server-side), then

      * scrape `/metrics` and assert valid Prometheus text format with the
        known series present — kernel dispatch/compile counters, the
        FLOPs/bytes cost gauges, and a server-side RPC latency histogram
        whose cumulative buckets are monotone with `+Inf` == `_count`;
      * scrape `/healthz` and assert it mirrors the gRPC Health state;
      * assert the sidecar's structured JSON log (NEMO_LOG_FILE) contains
        a record carrying the client's propagated trace id.
    """
    import importlib.util
    import subprocess
    import sys as _sys
    import urllib.request

    from nemo_tpu.obs import trace as obs_trace
    from nemo_tpu.utils.jax_config import pin_platform
    from nemo_tpu.utils.subproc import free_port, wait_listening

    if importlib.util.find_spec("grpc") is None:
        print(
            "obs-smoke: grpcio not installed; skipping (the smoke's whole "
            "surface is the sidecar)",
            file=sys.stderr,
        )
        return 0
    pin_platform("cpu")
    with tempfile.TemporaryDirectory(prefix="nemo_obs_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        # The Kernel-RPC assertions need dispatches to actually happen (and
        # the smoke must not write into the user's results cache).
        os.environ["NEMO_RESULT_CACHE"] = "off"
        log_path = os.path.join(tmp, "sidecar_log.jsonl")

        port, mport = free_port(), free_port()
        env = dict(os.environ, NEMO_LOG_FILE=log_path, NEMO_LOG_LEVEL="debug")
        env.pop("NEMO_TRACE", None)
        # The smoke's assertions need the Kernel-RPC route and the cost
        # capture: an operator's own NEMO_ANALYSIS_IMPL=sparse (client-side
        # routing, no Kernel RPCs) or NEMO_COST_ANALYSIS=0 (no FLOPs
        # gauges) must not fail `make validate` on a healthy tree.  Pinned
        # in the sidecar env AND (saved/restored) in this process, which
        # hosts the ServiceBackend client.
        for knob in ("NEMO_ANALYSIS_IMPL", "NEMO_COST_ANALYSIS"):
            env.pop(knob, None)
        prior_knobs = {
            k: os.environ.pop(k, None)
            for k in ("NEMO_ANALYSIS_IMPL", "NEMO_COST_ANALYSIS")
        }
        sidecar_log = os.path.join(tmp, "sidecar.stderr")
        log_fh = open(sidecar_log, "w")
        proc = subprocess.Popen(
            [_sys.executable, "-m", "nemo_tpu.service.server",
             "--port", str(port), "--platform", "cpu",
             "--metrics-port", str(mport)],
            stdout=log_fh,
            stderr=subprocess.STDOUT,
            env=env,
        )
        t = obs_trace.start_trace(os.path.join(tmp, "trace.json"))
        tid = t.trace_id
        problems: list[str] = []
        try:
            # Same listening-socket gate as trace_smoke (utils/subproc.py):
            # this environment's grpc wedges channels whose first connect
            # raced the bind.
            wait_listening(port, deadline_s=120.0, proc=proc)

            from nemo_tpu.analysis.pipeline import run_debug
            from nemo_tpu.backend.service_backend import ServiceBackend
            from nemo_tpu.models.case_studies import write_case_study

            corpus = write_case_study(
                "pb_asynchronous", n_runs=4, seed=7, out_dir=os.path.join(tmp, "corp")
            )
            run_debug(
                corpus, os.path.join(tmp, "results"), ServiceBackend(),
                conn=f"127.0.0.1:{port}", figures="none",
            )

            from nemo_tpu.obs import promexp

            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=15
            ) as resp:
                text = resp.read().decode("utf-8")
            fams = promexp.parse_prometheus_text(text)  # raises on bad lines
            for series in (
                "nemo_serve_kernel_calls_total",
                "nemo_kernel_dispatches_fused_total",
                "nemo_kernel_compiles_total",
            ):
                if series not in fams:
                    problems.append(f"/metrics missing series {series}")
            if not any(f.startswith("nemo_kernel_cost_flops") for f in fams):
                problems.append("/metrics has no kernel FLOPs cost gauge")
            hist = fams.get("nemo_serve_rpc_latency_s_Kernel")
            if hist is None:
                problems.append("/metrics has no server-side Kernel RPC latency histogram")
            else:
                buckets = [v for n, _, v in hist["samples"] if n.endswith("_bucket")]
                count = [v for n, _, v in hist["samples"] if n.endswith("_count")]
                if buckets != sorted(buckets):
                    problems.append("Kernel latency histogram buckets not monotone")
                if not count or buckets[-1] != count[0]:
                    problems.append("Kernel latency histogram +Inf bucket != count")

            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/healthz", timeout=15
            ) as resp:
                health = json.loads(resp.read().decode("utf-8"))
            if health.get("status") != "SERVING" or health.get("platform") != "cpu":
                problems.append(f"/healthz does not mirror Health state: {health}")

            correlated = []
            if os.path.exists(log_path):
                with open(log_path, "r", encoding="utf-8") as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            problems.append(f"unparseable sidecar log line: {line!r}")
                            break
                        if rec.get("trace_id") == tid:
                            correlated.append(rec)
            if not correlated:
                problems.append(
                    "no sidecar structured log record carries the propagated trace id"
                )
        except Exception as ex:
            if os.path.exists(sidecar_log):
                with open(sidecar_log, "r", encoding="utf-8") as fh:
                    print(
                        "obs-smoke: sidecar log tail:\n" + fh.read()[-3000:],
                        file=sys.stderr,
                    )
            print(f"obs-smoke: {type(ex).__name__}: {ex}", file=sys.stderr)
            return 1
        finally:
            for k, v in prior_knobs.items():
                if v is not None:
                    os.environ[k] = v
            obs_trace.finish()
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
            log_fh.close()
        if problems:
            print("obs-smoke: " + "; ".join(problems), file=sys.stderr)
            return 1
        print(
            f"obs-smoke: ok — {len(fams)} metric families scraped, healthz "
            f"SERVING, {len(correlated)} sidecar log record(s) correlated to "
            f"trace id {tid}"
        )
        return 0


def store_smoke() -> int:
    """Corpus-store smoke (`make store-smoke`, also the tail of `make
    validate`): cold-populate the persistent .npack store through a real
    pipeline run, then

      * a warm run must serve ingest from the store (store.hit, no miss)
        and produce a report tree BYTE-identical to a store-off run;
      * a deliberately corrupted shard must be rejected (store.stale, loud
        fallback to the parse path) while the report stays byte-identical,
        and the fallback must repopulate the store so the next run hits.
    """
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    # The corruption leg depends on the default verify/fingerprint policy;
    # an operator's own NEMO_STORE_VERIFY=off (the documented escape hatch)
    # must not turn a healthy tree into a red validate (the obs_smoke
    # NEMO_ANALYSIS_IMPL precedent).  Saved and restored.
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in ("NEMO_STORE_VERIFY", "NEMO_STORE_FINGERPRINT", "NEMO_STORE_WORKERS")
    }
    try:
        return _store_smoke_inner()
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def _store_smoke_inner() -> int:
    import glob

    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.store import CorpusStore

    with tempfile.TemporaryDirectory(prefix="nemo_store_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        # The warm-load parity legs exist to exercise the STORE's decode; a
        # report-cache hit would restore the tree without touching it.
        os.environ["NEMO_RESULT_CACHE"] = "off"
        cache = os.path.join(tmp, "corpus_cache")
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)

        def run(label: str, corpus_cache: str) -> tuple[dict[str, bytes], dict]:
            m0 = obs.metrics.snapshot()
            res = run_debug(
                corpus,
                os.path.join(tmp, label),
                JaxBackend(),
                figures="all",
                corpus_cache=corpus_cache,
            )
            delta = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            return _tree(res.report_dir), {
                k: v for k, v in delta.items() if k.startswith("store.")
            }

        problems: list[str] = []
        t_off, _ = run("off", "off")
        t_cold, m_cold = run("cold", cache)
        if not (m_cold.get("store.miss") and m_cold.get("store.populate")):
            problems.append(f"cold run did not populate the store: {m_cold}")
        t_warm, m_warm = run("warm", cache)
        if not m_warm.get("store.hit") or m_warm.get("store.miss"):
            problems.append(f"warm run was not served from the store: {m_warm}")

        def diverges(label: str, tree: dict[str, bytes]) -> None:
            if tree.keys() != t_off.keys():
                problems.append(
                    f"{label} file set diverges: {sorted(tree.keys() ^ t_off.keys())[:5]}"
                )
                return
            bad = sorted(k for k in t_off if t_off[k] != tree[k])
            if bad:
                problems.append(
                    f"{label} report DIVERGES from store-off in {len(bad)} "
                    f"file(s), e.g. {bad[:5]}"
                )

        diverges("cold-populate", t_cold)
        diverges("warm store load", t_warm)

        # Deliberate corruption: flip one byte mid-shard; the load must
        # reject it (stale), re-parse, repopulate, and the report must not
        # change by a byte.
        store_dir = CorpusStore(cache).store_dir(corpus)
        shards = sorted(glob.glob(os.path.join(store_dir, "seg-*", "strings_*.bin")))
        with open(shards[0], "r+b") as fh:
            fh.seek(os.path.getsize(shards[0]) // 2)
            byte = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([byte[0] ^ 0xFF]))
        t_corrupt, m_corrupt = run("corrupt", cache)
        if not m_corrupt.get("store.stale"):
            problems.append(f"corrupted shard was not rejected: {m_corrupt}")
        if not m_corrupt.get("store.populate"):
            problems.append(f"corrupt fallback did not repopulate: {m_corrupt}")
        diverges("corrupt-fallback", t_corrupt)
        t_again, m_again = run("again", cache)
        if not m_again.get("store.hit"):
            problems.append(f"store not healthy after repopulate: {m_again}")
        diverges("post-repopulate", t_again)

        if problems:
            print("store-smoke: " + "; ".join(problems), file=sys.stderr)
            return 1
        print(
            "store-smoke: ok — cold populate, warm mmap load, corrupted-shard "
            "rejection + repopulate all byte-identical to the store-off report "
            f"({len(t_off)} files)"
        )
        return 0


def delta_smoke() -> int:
    """Result-cache + incremental-delta smoke (`make delta-smoke`, also the
    tail of `make validate`): through real pipeline runs,

      * a warm repeat request (same store fingerprints + figure policy +
        ABI) must serve the FULL report from the result cache with ZERO
        kernel dispatches (kernel.dispatches.* metrics delta) and a report
        tree byte-identical to the cold run's;
      * after growing the corpus directory, only the new runs may map
        (delta.runs_mapped), the old segment's partial must merge from
        cache (rcache.partial_hit), and the merged report must be
        byte-identical to a from-scratch run of the grown corpus.
    """
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    # Same escape-hatch policy as store_smoke: operator NEMO_STORE_* /
    # NEMO_RESULT_CACHE* knobs must not red a healthy validate.
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_STORE_VERIFY",
            "NEMO_STORE_FINGERPRINT",
            "NEMO_STORE_WORKERS",
            "NEMO_RESULT_CACHE",
            "NEMO_RESULT_CACHE_MAX_GB",
        )
    }
    try:
        return _delta_smoke_inner()
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def _delta_smoke_inner() -> int:
    from nemo_tpu import obs
    from nemo_tpu.analysis.delta import kernel_dispatch_count
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, grow_corpus_dir, write_corpus

    with tempfile.TemporaryDirectory(prefix="nemo_delta_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        cc = os.path.join(tmp, "corpus_cache")
        rc = os.path.join(tmp, "result_cache")
        # 8 runs cover all four run kinds; the corpus dir starts at 6 and
        # GROWS to 8 (the incremental-sweep scenario).
        full = write_corpus(SynthSpec(n_runs=8, seed=2, eot=6), os.path.join(tmp, "full"))
        corpus = os.path.join(tmp, "grow", os.path.basename(full))
        grow_corpus_dir(full, corpus, 6)

        def run(label: str, corpus_cache: str = None, result_cache: str = None):
            m0 = obs.metrics.snapshot()
            res = run_debug(
                corpus,
                os.path.join(tmp, label),
                JaxBackend(),
                figures="all",
                corpus_cache=corpus_cache or cc,
                result_cache=result_cache or rc,
            )
            md = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            return _tree(res.report_dir), md

        problems: list[str] = []
        t_cold, m_cold = run("cold")
        if not m_cold.get("rcache.report_put"):
            problems.append(f"cold run did not populate the report cache: {m_cold}")
        t_warm, m_warm = run("warm")
        disp = kernel_dispatch_count(m_warm)
        if disp:
            problems.append(f"warm repeat dispatched {disp} kernels (want 0)")
        if not m_warm.get("rcache.report_hit"):
            problems.append(f"warm repeat was not a report-cache hit: {m_warm}")
        if t_warm != t_cold:
            bad = sorted(k for k in t_cold if t_cold.get(k) != t_warm.get(k))
            problems.append(
                f"warm-hit report diverges from cold in {len(bad)} file(s): {bad[:5]}"
            )

        # Grow the directory by 2 runs (the incremental sweep) and re-run:
        # only the new runs may map; the merged report must equal a
        # from-scratch analysis of the grown corpus, byte for byte.
        grow_corpus_dir(full, corpus, 8)
        t_grown, m_grown = run("grown")
        if m_grown.get("delta.runs_mapped") != 2 or m_grown.get("delta.runs_cached") != 6:
            problems.append(
                "grown run mapped "
                f"{m_grown.get('delta.runs_mapped')} runs / served "
                f"{m_grown.get('delta.runs_cached')} from cache (want 2/6)"
            )
        if not m_grown.get("rcache.partial_hit"):
            problems.append(f"grown run did not merge a cached partial: {m_grown}")
        t_scratch, _ = run("scratch", corpus_cache="off", result_cache="off")
        if t_grown.keys() != t_scratch.keys():
            problems.append(
                "grown-delta file set diverges from from-scratch: "
                f"{sorted(t_grown.keys() ^ t_scratch.keys())[:5]}"
            )
        else:
            bad = sorted(k for k in t_scratch if t_scratch[k] != t_grown[k])
            if bad:
                problems.append(
                    f"grown-delta report DIVERGES from from-scratch in "
                    f"{len(bad)} file(s), e.g. {bad[:5]}"
                )

        if problems:
            print("delta-smoke: " + "; ".join(problems), file=sys.stderr)
            return 1
        print(
            "delta-smoke: ok — warm repeat served the full report from cache "
            "with 0 kernel dispatches; the grown corpus mapped only its 2 new "
            f"runs and merged byte-identical to from-scratch ({len(t_scratch)} files)"
        )
        return 0


def shard_smoke() -> int:
    """Mesh-sharding + scheduler smoke (`make shard-smoke`, also a `make
    validate` step; ISSUE 7): on the 8-virtual-CPU-device platform, the
    mesh-sharded + scheduler-drained fused path must produce a report tree
    byte-identical to the single-device serial oracle (figures included),
    with kernel dispatches actually landing on >1 device and the
    analysis.sched.* decision series present.

    Runs under XLA_FLAGS=--xla_force_host_platform_device_count=8 (the
    Makefile target sets it); anything under 2 visible devices means the
    flag did not take and the smoke fails loudly rather than vacuously
    passing on one device."""
    import jax

    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    n_dev = len(jax.devices())
    if n_dev < 2:
        print(
            f"shard-smoke: only {n_dev} device(s) visible — "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 did not take",
            file=sys.stderr,
        )
        return 1

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="nemo_shard_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = "off"
        os.environ["NEMO_RESULT_CACHE"] = "off"
        # The dense route forced: the smoke is about the DEVICE lane (the
        # CPU platform's auto route would send every bucket to the sparse
        # host engine and the mesh would never engage); NEMO_MAX_BATCH=3
        # forces a bucket width that does not divide the mesh, so the
        # shard-multiple padding path is exercised too.
        os.environ["NEMO_ANALYSIS_IMPL"] = "dense"
        os.environ["NEMO_MAX_BATCH"] = "3"
        os.environ["NEMO_SCHED"] = "on"
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)

        os.environ["NEMO_SHARD"] = "0"
        oracle = run_debug(
            corpus, os.path.join(tmp, "oracle"), JaxBackend(), figures="all"
        )
        want = _tree(oracle.report_dir)

        os.environ["NEMO_SHARD"] = "1"
        m0 = obs.metrics.snapshot()
        sharded = run_debug(
            corpus, os.path.join(tmp, "sharded"), JaxBackend(), figures="all"
        )
        snap = obs.metrics.snapshot()
        mc = obs.Metrics.delta(snap, m0)["counters"]
        got = _tree(sharded.report_dir)

        if want.keys() != got.keys():
            problems.append(
                f"report file sets diverge: {sorted(want.keys() ^ got.keys())[:10]}"
            )
        else:
            bad = sorted(k for k in want if want[k] != got[k])
            if bad:
                problems.append(
                    f"sharded report diverges in {len(bad)} file(s), e.g. {bad[:5]}"
                )
        if not mc.get("kernel.sharded_dispatches"):
            problems.append("no dispatch took the mesh-sharded path")
        devices_used = snap["gauges"].get("analysis.shard.devices", 0)
        if devices_used < 2:
            problems.append(
                f"mesh spanned {devices_used} device(s); need >1 to call it sharded"
            )
        sched_series = [k for k in mc if k.startswith("analysis.sched.")]
        if not any(k.startswith("analysis.sched.dispatch.") for k in sched_series):
            problems.append(
                f"no analysis.sched.* dispatch series recorded: {sched_series}"
            )

    if problems:
        print("shard-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        f"shard-smoke: ok — {int(devices_used)}-device mesh report "
        f"byte-identical to the single-device oracle ({len(want)} files), "
        f"{int(mc.get('kernel.sharded_dispatches', 0))} sharded dispatch(es), "
        f"scheduler series {sorted(sched_series)}"
    )
    return 0


def sparse_device_smoke() -> int:
    """Sparse-CSR device-kernel smoke (`make sparse-device-smoke`, also the
    tail of `make validate`; ISSUE 10):

      * a forced NEMO_ANALYSIS_IMPL=sparse_device pipeline must produce a
        report tree BYTE-identical to the forced-dense oracle (figures
        included), with an ``analysis.route.<verb>.sparse_device`` record
        for every dispatched verb (fused + diff);
      * a giant-V corpus under the same umbrella must dispatch its giant
        runs on the DEVICE sparse route (``analysis.route.giant.
        sparse_device``) — not the host fallback — byte-identical to the
        host-routed giant run;
      * two watermark SUBPROCESSES analyzing a giant-V corpus (dense vs
        sparse_device) must show the sparse route's analysis-phase memory
        watermark (``mem.host_peak_rss_bytes`` delta — on a CPU container
        the device buffers ARE host memory) at least 5x below the dense
        route's.
    """
    import subprocess

    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    # Operator route/kernel pins must not red (or vacuously green) a
    # healthy validate — the smoke owns these knobs for its duration.
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_ANALYSIS_IMPL",
            "NEMO_ANALYSIS_HOST_WORK",
            "NEMO_GIANT_IMPL",
            "NEMO_GIANT_V",
            "NEMO_SPARSE_WAVE_IMPL",
            "NEMO_SPARSE_DEVICE_MEM_MB",
            "NEMO_SPARSE_DEVICE_DENSITY",
            "NEMO_SPARSE_DEVICE_MIN_V",
            "NEMO_SCHED",
            "NEMO_MAX_BATCH",
            "NEMO_SHARD",
        )
    }
    problems: list[str] = []
    try:
        with tempfile.TemporaryDirectory(prefix="nemo_sdev_smoke_") as tmp:
            os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
            os.environ["NEMO_CORPUS_CACHE"] = "off"
            os.environ["NEMO_RESULT_CACHE"] = "off"

            # ---- (a) forced-route byte parity + per-verb route records
            corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)
            os.environ["NEMO_ANALYSIS_IMPL"] = "dense"
            dense = run_debug(
                corpus, os.path.join(tmp, "dense"), JaxBackend(), figures="all"
            )
            t_dense = _tree(dense.report_dir)
            os.environ["NEMO_ANALYSIS_IMPL"] = "sparse_device"
            m0 = obs.metrics.snapshot()
            sd = run_debug(corpus, os.path.join(tmp, "sd"), JaxBackend(), figures="all")
            mc = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            t_sd = _tree(sd.report_dir)
            if t_dense.keys() != t_sd.keys():
                problems.append(
                    f"(a) file sets diverge: {sorted(t_dense.keys() ^ t_sd.keys())[:5]}"
                )
            else:
                bad = sorted(k for k in t_dense if t_dense[k] != t_sd[k])
                if bad:
                    problems.append(
                        f"(a) sparse-device report DIVERGES from dense in "
                        f"{len(bad)} file(s), e.g. {bad[:5]}"
                    )
            for verb in ("fused", "diff"):
                if not mc.get(f"analysis.route.{verb}.sparse_device"):
                    problems.append(
                        f"(a) no analysis.route.{verb}.sparse_device recorded: "
                        f"{ {k: v for k, v in mc.items() if 'route' in k} }"
                    )

            # ---- (b) giant bucket dispatches on DEVICE, not the host hatch
            giant_dir = write_corpus(
                SynthSpec(n_runs=5, seed=4, eot=40, name="giantish"), tmp
            )
            os.environ["NEMO_GIANT_V"] = "64"
            os.environ.pop("NEMO_ANALYSIS_IMPL", None)
            host_run = run_debug(
                giant_dir, os.path.join(tmp, "giant_host"), JaxBackend(), figures="all"
            )
            os.environ["NEMO_ANALYSIS_IMPL"] = "sparse_device"
            be = JaxBackend()
            m0 = obs.metrics.snapshot()
            sd_run = run_debug(
                giant_dir, os.path.join(tmp, "giant_sd"), be, figures="all"
            )
            mg = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            if not mg.get("analysis.route.giant.sparse_device"):
                problems.append(
                    f"(b) giant runs did not dispatch on the device sparse "
                    f"route: { {k: v for k, v in mg.items() if 'giant' in k} }"
                )
            if mg.get("analysis.route.giant.sparse"):
                problems.append("(b) giant runs still took the host fallback")
            th, ts = _tree(host_run.report_dir), _tree(sd_run.report_dir)
            bad = sorted(k for k in th if th.get(k) != ts.get(k))
            if th.keys() != ts.keys() or bad:
                problems.append(
                    f"(b) giant sparse-device report diverges from host-routed "
                    f"in {len(bad)} file(s), e.g. {bad[:5]}"
                )
            os.environ.pop("NEMO_ANALYSIS_IMPL", None)
            os.environ.pop("NEMO_GIANT_V", None)

            # ---- (c) watermark children: sparse >=5x below dense
            child = r"""
import json, os, resource, sys, tempfile, time
impl = sys.argv[1]
os.environ["NEMO_ANALYSIS_IMPL"] = impl
os.environ["NEMO_GIANT_V"] = "1024"
from nemo_tpu.ingest.molly import load_molly_output
from nemo_tpu.backend.jax_backend import JaxBackend, sample_memory_watermarks
def rss():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
molly = load_molly_output(sys.argv[2])
be = JaxBackend()
be.init_graph_db("", molly)
r0 = rss()
t0 = time.time()
be._fused()
wm = sample_memory_watermarks()
print(json.dumps({
    "impl": impl,
    "analysis_peak_delta_bytes": wm["host_peak_rss_bytes"] - r0,
    "device_peak_bytes": wm.get("device_peak_bytes"),
    "wall_s": round(time.time() - t0, 2),
}))
"""
            wm_dir = write_corpus(
                SynthSpec(n_runs=3, seed=3, eot=4800, name="giantv"), tmp
            )
            deltas: dict[str, dict] = {}
            for impl in ("sparse_device", "dense"):
                env = dict(os.environ, JAX_PLATFORMS="cpu")
                proc = subprocess.run(
                    [sys.executable, "-c", child, impl, wm_dir],
                    capture_output=True,
                    text=True,
                    timeout=600,
                    env=env,
                )
                if proc.returncode != 0:
                    problems.append(
                        f"(c) {impl} watermark child failed rc={proc.returncode}: "
                        f"{proc.stderr[-500:]}"
                    )
                    continue
                deltas[impl] = json.loads(proc.stdout.strip().splitlines()[-1])
            if len(deltas) == 2:
                d_dense = deltas["dense"]["analysis_peak_delta_bytes"]
                d_sparse = deltas["sparse_device"]["analysis_peak_delta_bytes"]
                if d_sparse * 5 > d_dense:
                    problems.append(
                        f"(c) sparse-device watermark not 5x below dense: "
                        f"dense {d_dense >> 20} MB vs sparse {max(d_sparse, 0) >> 20} MB"
                    )
    finally:
        for k in (
            "NEMO_ANALYSIS_IMPL",
            "NEMO_GIANT_IMPL",
            "NEMO_GIANT_V",
        ):
            os.environ.pop(k, None)
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v
    if problems:
        print("sparse-device-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    # Readout ratio floors the sparse delta at 1 MB: a sparse analysis that
    # never grew the process peak at all (the common case — ingest already
    # peaked higher) would otherwise print a meaningless astronomic ratio.
    ratio = (
        deltas["dense"]["analysis_peak_delta_bytes"]
        / max(deltas["sparse_device"]["analysis_peak_delta_bytes"], 1 << 20)
        if len(deltas) == 2
        else float("nan")
    )
    print(
        "sparse-device-smoke: ok — forced sparse_device report byte-identical "
        "to dense (routes recorded for fused+diff), giant runs dispatched on "
        f"the device sparse route, and the giant-V watermark dropped {ratio:.1f}x "
        f"(dense {deltas['dense']['analysis_peak_delta_bytes'] >> 20} MB wall "
        f"{deltas['dense']['wall_s']} s vs sparse "
        f"{deltas['sparse_device']['analysis_peak_delta_bytes'] >> 20} MB wall "
        f"{deltas['sparse_device']['wall_s']} s)"
    )
    return 0


def serve_smoke() -> int:
    """Serving-tier smoke (`make serve-smoke`, also the tail of `make
    validate`; ISSUE 8): boot a `--max-inflight 2` sidecar SUBPROCESS and

      * fire 6 concurrent clients (3 identical directories, 3 distinct)
        and assert EXACTLY ONE underlying analysis served the identical
        trio (single-flight coalescing: serve.analyze_chunks == 4,
        serve.coalesce.hit == 2), with the trio's responses byte-equal
        and zero failed/rejected requests;
      * assert the serve.* series (queue/inflight gauges, coalesce
        counters, queued-vs-executing latency histograms) are live on
        `/metrics`;
      * send SIGTERM while one more request is in flight and assert the
        drain contract: `/healthz` flips NOT_SERVING, the in-flight
        request completes, and the process exits 0.
    """
    import importlib.util
    import signal
    import subprocess
    import sys as _sys
    import threading
    import time as _time
    import urllib.error
    import urllib.request

    from nemo_tpu.utils.jax_config import pin_platform
    from nemo_tpu.utils.subproc import free_port, wait_listening

    if importlib.util.find_spec("grpc") is None:
        print(
            "serve-smoke: grpcio not installed; skipping (the smoke's whole "
            "surface is the sidecar)",
            file=sys.stderr,
        )
        return 0
    pin_platform("cpu")
    # The assertions depend on the serving defaults; an operator's own
    # NEMO_SERVE_* pins must not red a healthy validate (the obs_smoke
    # NEMO_ANALYSIS_IMPL precedent).  Saved and restored.
    serve_knobs = (
        "NEMO_SERVE_INFLIGHT",
        "NEMO_SERVE_QUEUE",
        "NEMO_SERVE_DRAIN_S",
        "NEMO_SERVE_COALESCE_LINGER_S",
        "NEMO_SERVE_BATCH_WINDOW_MS",
        "NEMO_RESULT_CACHE",
        "NEMO_CORPUS_CACHE",
    )
    prior_knobs = {k: os.environ.pop(k, None) for k in serve_knobs}
    try:
        with tempfile.TemporaryDirectory(prefix="nemo_serve_smoke_") as tmp:
            from nemo_tpu.models.synth import SynthSpec, write_corpus
            from nemo_tpu.obs import promexp
            from nemo_tpu.service.client import RemoteAnalyzer

            shared = write_corpus(SynthSpec(n_runs=5, seed=41, name="shared"), tmp)
            distinct = [
                write_corpus(SynthSpec(n_runs=5, seed=42 + i, name=f"solo{i}"), tmp)
                for i in range(3)
            ]
            drain_dir = write_corpus(SynthSpec(n_runs=12, seed=49, name="drain"), tmp)

            port, mport = free_port(), free_port()
            log_path = os.path.join(tmp, "sidecar_log.jsonl")
            env = dict(
                os.environ,
                NEMO_LOG_FILE=log_path,
                # Server-side corpus store ON (the content address the
                # single-flight keys on needs segment fingerprints);
                # result cache OFF so the dedup below is attributable to
                # COALESCING alone; a generous linger makes the trio
                # deterministic even if admission staggers them.
                NEMO_CORPUS_CACHE=os.path.join(tmp, "corpus_cache"),
                NEMO_RESULT_CACHE="off",
                NEMO_SERVE_COALESCE_LINGER_S="60",
            )
            env.pop("NEMO_TRACE", None)
            sidecar_log = os.path.join(tmp, "sidecar.stderr")
            log_fh = open(sidecar_log, "w")
            proc = subprocess.Popen(
                [_sys.executable, "-m", "nemo_tpu.service.server",
                 "--port", str(port), "--platform", "cpu",
                 "--metrics-port", str(mport), "--max-inflight", "2"],
                stdout=log_fh,
                stderr=subprocess.STDOUT,
                env=env,
            )
            problems: list[str] = []
            try:
                # Socket gate before any channel (utils/subproc.py: this
                # env's grpc wedges channels that raced the bind).
                wait_listening(port, deadline_s=120.0, proc=proc)

                target = f"127.0.0.1:{port}"
                with RemoteAnalyzer(target=target) as probe:
                    probe.wait_ready(60.0)

                def scrape() -> dict:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/metrics", timeout=15
                    ) as resp:
                        return promexp.parse_prometheus_text(resp.read().decode("utf-8"))

                def sample(fams: dict, name: str) -> float:
                    fam = fams.get(name)
                    if not fam:
                        return 0.0
                    return fam["samples"][0][2]

                # 6 concurrent clients: 3 identical (the coalescing trio)
                # + 3 distinct, all against a --max-inflight 2 sidecar.
                payloads: list = [None] * 6
                failures: list = []

                def client_thread(i: int, d: str) -> None:
                    try:
                        with RemoteAnalyzer(target=target, tenant=f"t{i % 2}") as c:
                            resp, _ = c._call(
                                c._analyze_dir, {"dir": d}, name="AnalyzeDir"
                            )
                            payloads[i] = resp.SerializeToString()
                    except Exception as ex:
                        failures.append(f"client {i}: {type(ex).__name__}: {ex}")

                dirs = [shared, shared, shared] + distinct
                threads = [
                    threading.Thread(target=client_thread, args=(i, d))
                    for i, d in enumerate(dirs)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=300)
                if failures:
                    problems.append("; ".join(failures))
                elif any(p is None for p in payloads):
                    problems.append("a client thread never finished")
                else:
                    trio = set(payloads[:3])
                    if len(trio) != 1:
                        problems.append(
                            "identical trio responses are NOT byte-equal"
                        )
                    fams = scrape()
                    chunks = sample(fams, "nemo_serve_analyze_chunks_total")
                    if chunks != 4:
                        problems.append(
                            f"expected exactly 4 underlying analyses (1 shared "
                            f"+ 3 distinct), metrics say {chunks}"
                        )
                    hits = sample(fams, "nemo_serve_coalesce_hit_total")
                    if hits != 2:
                        problems.append(f"expected 2 coalesce hits, got {hits}")
                    if sample(fams, "nemo_serve_rejected_total"):
                        problems.append("requests were rejected under the default queue")
                    for series in (
                        "nemo_serve_queue_depth",
                        "nemo_serve_inflight",
                        "nemo_serve_coalesce_leader_total",
                        "nemo_serve_queued_s",
                        "nemo_serve_exec_s",
                        "nemo_serve_tenant_t0_requests_total",
                    ):
                        if series not in fams:
                            problems.append(f"/metrics missing serve series {series}")

                # Drain: one more (cold, so slow) request in flight, then
                # SIGTERM — NOT_SERVING on /healthz, request completes,
                # clean exit.
                drained_result: list = []

                def drain_client() -> None:
                    try:
                        with RemoteAnalyzer(target=target) as c:
                            drained_result.append(c.analyze_dir_remote(drain_dir))
                    except Exception as ex:
                        drained_result.append(ex)

                admitted_before = sample(scrape(), "nemo_serve_admitted_total")
                dt = threading.Thread(target=drain_client)
                dt.start()
                deadline = _time.monotonic() + 60.0
                while sample(scrape(), "nemo_serve_admitted_total") <= admitted_before:
                    if _time.monotonic() > deadline:
                        problems.append("drain request never admitted")
                        break
                    _time.sleep(0.05)
                proc.send_signal(signal.SIGTERM)
                not_serving = False
                deadline = _time.monotonic() + 30.0
                while _time.monotonic() < deadline and proc.poll() is None:
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/healthz", timeout=5
                        ) as resp:
                            doc = json.loads(resp.read().decode("utf-8"))
                            if doc.get("status") == "NOT_SERVING":
                                not_serving = True
                                break
                    except urllib.error.HTTPError as ex:
                        if ex.code == 503:
                            not_serving = True
                            break
                    except OSError:
                        break  # httpd already down: rely on rc + log below
                    _time.sleep(0.05)
                dt.join(timeout=120)
                rc = proc.wait(timeout=120)
                if not drained_result or isinstance(drained_result[0], Exception):
                    problems.append(
                        f"in-flight request did not survive the drain: "
                        f"{drained_result[:1]}"
                    )
                if rc != 0:
                    problems.append(f"sidecar exited rc={rc} after SIGTERM drain")
                drain_logged = False
                if os.path.exists(log_path):
                    with open(log_path, "r", encoding="utf-8") as fh:
                        for line in fh:
                            try:
                                rec = json.loads(line)
                            except json.JSONDecodeError:
                                continue
                            if rec.get("event") == "sidecar.drained" and rec.get("clean"):
                                drain_logged = True
                if not (not_serving or drain_logged):
                    problems.append(
                        "no NOT_SERVING observed during drain and no clean "
                        "sidecar.drained log record"
                    )
            except Exception as ex:
                if os.path.exists(sidecar_log):
                    with open(sidecar_log, "r", encoding="utf-8") as fh:
                        print(
                            "serve-smoke: sidecar log tail:\n" + fh.read()[-3000:],
                            file=sys.stderr,
                        )
                print(f"serve-smoke: {type(ex).__name__}: {ex}", file=sys.stderr)
                return 1
            finally:
                if proc.poll() is None:
                    proc.terminate()
                    try:
                        proc.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait(timeout=15)
                log_fh.close()
            if problems:
                print("serve-smoke: " + "; ".join(problems), file=sys.stderr)
                return 1
            print(
                "serve-smoke: ok — 3 identical concurrent requests coalesced "
                "into 1 analysis (2 hits, byte-equal responses), 4 analyses "
                "total for 6 clients, serve.* series live on /metrics, and a "
                "SIGTERM drain finished its in-flight request and exited clean"
            )
            return 0
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def fleet_smoke() -> int:
    """Fleet scale-out smoke (`make fleet-smoke`, also the tail of `make
    validate`; ISSUE 14): boot TWO sidecar replicas joined by a shared
    result-cache tier, plus the thin consistent-hash router, and assert

      * a cold-corpus herd hitting BOTH replicas concurrently (2 clients
        -> replica 0, 1 client -> replica 1, same corpus) is served with
        EXACTLY ONE analysis fleet-wide — the cross-replica single-flight
        leader lease in the shared tier — and byte-identical responses
        from both replicas;
      * the replica that never analyzed the corpus then serves it WARM
        from the shared tier: trailing `nemo-rcache: hit`, zero kernel
        dispatches, same bytes;
      * the router proxies AnalyzeDir with stable affinity (a repeat of
        the same corpus lands on the same replica, as an rcache hit) and
        its router.* series are live on /metrics;
      * router HA (ISSUE 15): a SECOND router sharing the same backend
        list computes IDENTICAL affinity — the ring is a pure function of
        the backend set, so N routers are stateless peers — proven by the
        warm corpus served through router 2 hitting the same replica's
        rcache with zero re-analyses;
      * SIGTERM drains the whole fleet cleanly (both routers and both
        replicas exit 0).
    """
    import importlib.util
    import signal
    import subprocess
    import sys as _sys
    import threading
    import urllib.request

    from nemo_tpu.utils.jax_config import pin_platform
    from nemo_tpu.utils.subproc import PortReservation, free_port, wait_listening

    if importlib.util.find_spec("grpc") is None:
        print(
            "fleet-smoke: grpcio not installed; skipping (the smoke's whole "
            "surface is the sidecar fleet)",
            file=sys.stderr,
        )
        return 0
    pin_platform("cpu")
    fleet_knobs = (
        "NEMO_SERVE_INFLIGHT",
        "NEMO_SERVE_QUEUE",
        "NEMO_SERVE_DRAIN_S",
        "NEMO_SERVE_COALESCE_LINGER_S",
        "NEMO_RESULT_CACHE",
        "NEMO_RCACHE_SHARED",
        "NEMO_CORPUS_CACHE",
        "NEMO_LEASE_TTL_S",
        "NEMO_FLEET_REPLICAS",
        "NEMO_SERVE_PREWARM",
    )
    prior_knobs = {k: os.environ.pop(k, None) for k in fleet_knobs}
    try:
        with tempfile.TemporaryDirectory(prefix="nemo_fleet_smoke_") as tmp:
            from nemo_tpu.models.synth import SynthSpec, write_corpus
            from nemo_tpu.service.client import RemoteAnalyzer

            herd_dir = write_corpus(SynthSpec(n_runs=5, seed=61, name="herd"), tmp)
            solo_dir = write_corpus(SynthSpec(n_runs=5, seed=62, name="solo"), tmp)
            shared_cache = os.path.join(tmp, "shared_rcache")

            def replica_env(i: int) -> dict:
                return dict(
                    os.environ,
                    NEMO_LOG_FILE=os.path.join(tmp, f"replica{i}_log.jsonl"),
                    # Per-replica local caches + ONE shared tier: the
                    # cross-replica dedup below must flow through the
                    # shared tier, not an accidentally shared local root.
                    NEMO_CORPUS_CACHE=os.path.join(tmp, f"corpus_cache{i}"),
                    NEMO_RESULT_CACHE=os.path.join(tmp, f"result_cache{i}"),
                    NEMO_RCACHE_SHARED=shared_cache,
                    # One persistent compile cache across the fleet — the
                    # warm-boot story's disk tier.
                    NEMO_JAX_CACHE=os.path.join(tmp, "jax_cache"),
                )

            procs: list = []
            log_fhs: list = []

            def boot(args: list, env: dict, name: str):
                fh = open(os.path.join(tmp, f"{name}.stderr"), "w")
                log_fhs.append(fh)
                p = subprocess.Popen(
                    [_sys.executable, "-m", "nemo_tpu.service.server", *args],
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    env=env,
                )
                procs.append(p)
                return p

            problems: list[str] = []
            ports = PortReservation(3)  # the satellite fix in action
            rports = [ports.ports[0], ports.ports[1]]
            router_port = ports.ports[2]
            mport = free_port()
            try:
                replicas = []
                for i in range(2):
                    ports.release(i)
                    replicas.append(
                        boot(
                            ["--port", str(rports[i]), "--platform", "cpu"],
                            replica_env(i),
                            f"replica{i}",
                        )
                    )
                for i in range(2):
                    wait_listening(rports[i], deadline_s=120.0, proc=replicas[i])
                targets = [f"127.0.0.1:{p}" for p in rports]
                for t in targets:
                    with RemoteAnalyzer(target=t) as c:
                        c.wait_ready(60.0)
                ports.release(2)
                router = boot(
                    [
                        "--router",
                        "--port", str(router_port),
                        "--backends", ",".join(targets),
                        "--metrics-port", str(mport),
                    ],
                    dict(os.environ, NEMO_LOG_FILE=os.path.join(tmp, "router_log.jsonl")),
                    "router",
                )
                wait_listening(router_port, deadline_s=60.0, proc=router)
                router_target = f"127.0.0.1:{router_port}"
                with RemoteAnalyzer(target=router_target) as c:
                    c.wait_ready(60.0)  # Health proxied through the router

                def replica_counters(t: str) -> dict:
                    with RemoteAnalyzer(target=t) as c:
                        return c.health().get("metrics", {}).get("counters", {})

                def dispatches(counters: dict) -> int:
                    from nemo_tpu.analysis.delta import kernel_dispatch_count

                    return kernel_dispatch_count(counters)

                # ---- 1. Cold herd ACROSS replicas: one analysis fleet-wide.
                payloads: list = [None] * 3
                trailings: list = [None] * 3
                failures: list = []

                def herd_client(i: int, target: str) -> None:
                    try:
                        with RemoteAnalyzer(target=target) as c:
                            resp, call = c._call(
                                c._analyze_dir, {"dir": herd_dir}, name="AnalyzeDir"
                            )
                            payloads[i] = resp.SerializeToString()
                            trailings[i] = dict(call.trailing_metadata() or ())
                    except Exception as ex:
                        failures.append(f"herd client {i}: {type(ex).__name__}: {ex}")

                herd_targets = [targets[0], targets[0], targets[1]]
                threads = [
                    threading.Thread(target=herd_client, args=(i, t))
                    for i, t in enumerate(herd_targets)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=300)
                if failures:
                    problems.append("; ".join(failures))
                elif any(p is None for p in payloads):
                    problems.append("a herd client never finished")
                else:
                    if len(set(payloads)) != 1:
                        problems.append(
                            "herd responses are NOT byte-identical across replicas"
                        )
                    after = [replica_counters(t) for t in targets]
                    chunks = [int(c.get("serve.analyze_chunks", 0)) for c in after]
                    if sum(chunks) != 1:
                        problems.append(
                            f"expected exactly ONE analysis fleet-wide for the "
                            f"herd, replicas report {chunks}"
                        )
                    leaders = [int(c.get("serve.fleet.leader", 0)) for c in after]
                    followers = [int(c.get("serve.fleet.follower", 0)) for c in after]
                    if sum(leaders) != 1 or sum(followers) < 1:
                        problems.append(
                            f"fleet single-flight counters off: leaders={leaders} "
                            f"followers={followers}"
                        )

                    # ---- 2. The NON-leader replica serves the corpus warm
                    # from the shared tier with zero kernel dispatches.
                    non_leader = chunks.index(0)
                    before = replica_counters(targets[non_leader])
                    with RemoteAnalyzer(target=targets[non_leader]) as c:
                        resp, call = c._call(
                            c._analyze_dir, {"dir": herd_dir}, name="AnalyzeDir"
                        )
                        warm_payload = resp.SerializeToString()
                        warm_md = dict(call.trailing_metadata() or ())
                    now = replica_counters(targets[non_leader])
                    if warm_md.get("nemo-rcache") != "hit":
                        problems.append(
                            f"non-leader warm request was not an rcache hit "
                            f"(nemo-rcache={warm_md.get('nemo-rcache')!r})"
                        )
                    if dispatches(now) - dispatches(before) != 0:
                        problems.append(
                            "non-leader replica dispatched kernels serving a "
                            "shared-tier warm corpus"
                        )
                    if int(now.get("rcache.blob_analyze_dir_shared_hit", 0)) < 1:
                        problems.append(
                            "non-leader served the warm corpus without a "
                            "shared-tier hit (local alias?)"
                        )
                    # Identical modulo the timing field: a warm rcache hit
                    # reports step_seconds=0 (it dispatched nothing) while
                    # the herd's bytes carry the leader's real wall — and
                    # the hit path re-serializes in the serving replica, so
                    # the comparison must be MESSAGE equality (map-field
                    # byte order is process-dependent), not byte equality.
                    # The herd trio above IS compared byte-for-byte: those
                    # responses relay one serialization verbatim.
                    from nemo_tpu.service.proto import nemo_service_pb2 as _pb

                    herd_resp = _pb.AnalyzeResponse.FromString(payloads[0])
                    herd_resp.step_seconds = 0.0
                    warm_resp = _pb.AnalyzeResponse.FromString(warm_payload)
                    if warm_resp.step_seconds != 0.0:
                        problems.append(
                            "warm rcache hit reported a nonzero step wall"
                        )
                    warm_resp.step_seconds = 0.0
                    if warm_resp != herd_resp:
                        problems.append(
                            "shared-tier warm response diverges from the herd's"
                        )

                # ---- 3. Router: proxy + stable affinity (repeat = rcache
                # hit on the SAME replica) + live router.* metrics.
                try:
                    base = [replica_counters(t) for t in targets]
                    with RemoteAnalyzer(target=router_target) as c:
                        r1 = c.analyze_dir_remote(solo_dir)
                        before = [replica_counters(t) for t in targets]
                        r2 = c.analyze_dir_remote(solo_dir)
                        after = [replica_counters(t) for t in targets]
                    del r1, r2
                    solo_chunks = [
                        int(b.get("serve.analyze_chunks", 0))
                        - int(z.get("serve.analyze_chunks", 0))
                        for b, z in zip(before, base)
                    ]
                    hits = [
                        int(a.get("rcache.blob_analyze_dir_hit", 0))
                        - int(b.get("rcache.blob_analyze_dir_hit", 0))
                        for a, b in zip(after, before)
                    ]
                    # STABLE affinity means the repeat lands on the SAME
                    # replica that analyzed (a shared-tier hit on the
                    # other replica would also sum to 1 — vacuous).
                    if solo_chunks.count(1) != 1 or sum(solo_chunks) != 1:
                        problems.append(
                            f"router solo corpus not analyzed exactly once: "
                            f"{solo_chunks}"
                        )
                    elif hits[solo_chunks.index(1)] != 1 or sum(hits) != 1:
                        problems.append(
                            f"router repeat did not hit the SAME replica that "
                            f"analyzed (affinity broken): chunks={solo_chunks} "
                            f"hits={hits}"
                        )
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/metrics", timeout=15
                    ) as resp:
                        text = resp.read().decode("utf-8")
                    if "nemo_router_routed_AnalyzeDir" not in text:
                        problems.append("router /metrics missing router.routed series")

                    # ---- 3b. Router HA (ISSUE 15): N routers sharing the
                    # consistent-hash ring are stateless BY CONSTRUCTION —
                    # boot a SECOND router over the same backends and
                    # assert identical affinity: the same corpus through
                    # router 2 lands on the SAME replica that analyzed it
                    # via router 1 (an rcache hit there, zero analyses
                    # anywhere).
                    r2_port = free_port()
                    router2 = boot(
                        [
                            "--router",
                            "--port", str(r2_port),
                            "--backends", ",".join(targets),
                        ],
                        dict(
                            os.environ,
                            NEMO_LOG_FILE=os.path.join(tmp, "router2_log.jsonl"),
                        ),
                        "router2",
                    )
                    wait_listening(r2_port, deadline_s=60.0, proc=router2)
                    before2 = [replica_counters(t) for t in targets]
                    with RemoteAnalyzer(target=f"127.0.0.1:{r2_port}") as c:
                        c.wait_ready(60.0)
                        c.analyze_dir_remote(solo_dir)
                    after2 = [replica_counters(t) for t in targets]
                    chunks2 = [
                        int(a.get("serve.analyze_chunks", 0))
                        - int(b.get("serve.analyze_chunks", 0))
                        for a, b in zip(after2, before2)
                    ]
                    hits2 = [
                        int(a.get("rcache.blob_analyze_dir_hit", 0))
                        - int(b.get("rcache.blob_analyze_dir_hit", 0))
                        for a, b in zip(after2, before2)
                    ]
                    if sum(chunks2) != 0:
                        problems.append(
                            f"second router re-analyzed an already-warm "
                            f"corpus (affinity diverged): {chunks2}"
                        )
                    elif (
                        solo_chunks.count(1) == 1
                        and hits2[solo_chunks.index(1)] != 1
                    ):
                        problems.append(
                            f"second router's request did not land on the "
                            f"replica router 1 pinned (affinity not "
                            f"identical): chunks1={solo_chunks} hits2={hits2}"
                        )
                except Exception as ex:
                    problems.append(f"router leg failed: {type(ex).__name__}: {ex}")

                # ---- 4. Clean drain of the whole fleet (router 2 included).
                proc_names = ("replica0", "replica1", "router", "router2")
                for p in procs:
                    p.send_signal(signal.SIGTERM)
                for name, p in zip(proc_names, procs):
                    try:
                        rc = p.wait(timeout=60)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait(timeout=15)
                        problems.append(f"{name} did not drain inside 60s")
                        continue
                    if rc != 0:
                        problems.append(f"{name} exited rc={rc} after SIGTERM")
            except Exception as ex:
                for name in ("replica0", "replica1", "router", "router2"):
                    path = os.path.join(tmp, f"{name}.stderr")
                    if os.path.exists(path):
                        with open(path, "r", encoding="utf-8") as fh:
                            tail = fh.read()[-1500:]
                        if tail.strip():
                            print(f"fleet-smoke: {name} log tail:\n{tail}", file=sys.stderr)
                print(f"fleet-smoke: {type(ex).__name__}: {ex}", file=sys.stderr)
                return 1
            finally:
                ports.close()
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                        try:
                            p.wait(timeout=15)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait(timeout=15)
                for fh in log_fhs:
                    fh.close()
            if problems:
                print("fleet-smoke: " + "; ".join(problems), file=sys.stderr)
                return 1
            print(
                "fleet-smoke: ok — a cold herd across 2 replicas cost the "
                "fleet ONE analysis (shared-tier leader lease), responses "
                "byte-identical, the non-leader replica served the corpus "
                "warm with zero dispatches, the router proxied with stable "
                "affinity, a second router computed identical affinity "
                "(stateless ring), and the whole fleet drained clean"
            )
            return 0
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def obs_fleet_smoke() -> int:
    """Fleet-observability smoke (`make obs-fleet-smoke`, also the tail of
    `make validate`; ISSUE 17): boot TWO replicas plus the router with
    --metrics-port and assert the four legs of the observability plane:

      * **federation** — the router's /metrics page parses as conformant
        Prometheus text and carries BOTH replicas' series under
        ``{replica="host:port"}`` labels, ``nemo_fleet_*`` rollups
        (counters summed, the ``serve.capacity`` gauge envelope), and the
        ``nemo_fleet_backend_up`` / ``nemo_fleet_backends_up`` liveness
        gauges (obs/federation.py);
      * **trace stitching** — ONE traced warm AnalyzeDir through the
        router yields ONE trace file holding the client's rpc span, the
        router's forward span, and the replica's admission + serve spans,
        from >=3 distinct pids (serve/router.py, service/server.py);
      * **flight recorder** — replica 0 boots with an injected chaos fault
        (``NEMO_CHAOS=fail_dispatch:2`` + ``NEMO_BREAKER_FAILURES=2``);
        its first two batched Kernel dispatches fail on the device lane
        (serve/batch.py -> parallel/sched.py), tripping the breaker, which
        dumps exactly ONE ``flightrec-breaker_trip-*.json`` bundle — and
        the SAME kernel call then succeeds, closing the breaker
        (obs/flight.py);
      * **autoscale** — a shed surge against a 1-slot/0-queue replica
        flips the router's /autoscale recommendation to +1, and going idle
        flips it back down through the hold-count hysteresis
        (serve/autoscale.py);

    then SIGTERM drains the whole fleet cleanly (every process exits 0).
    """
    import glob
    import importlib.util
    import signal
    import subprocess
    import sys as _sys
    import threading
    import time as _time
    import urllib.request

    from nemo_tpu.utils.jax_config import pin_platform
    from nemo_tpu.utils.subproc import PortReservation, free_port, wait_listening

    if importlib.util.find_spec("grpc") is None:
        print(
            "obs-fleet-smoke: grpcio not installed; skipping (the smoke's "
            "whole surface is the sidecar fleet)",
            file=sys.stderr,
        )
        return 0
    pin_platform("cpu")
    knobs = (
        "NEMO_SERVE_INFLIGHT",
        "NEMO_SERVE_QUEUE",
        "NEMO_SERVE_DRAIN_S",
        "NEMO_SERVE_COALESCE_LINGER_S",
        "NEMO_SERVE_PREWARM",
        "NEMO_RESULT_CACHE",
        "NEMO_RCACHE_SHARED",
        "NEMO_CORPUS_CACHE",
        "NEMO_FLEET_REPLICAS",
        "NEMO_CHAOS",
        "NEMO_BREAKER_FAILURES",
        "NEMO_FLIGHT",
        "NEMO_FLIGHT_DIR",
        "NEMO_FLIGHT_COOLDOWN_S",
        "NEMO_ROUTER_HEALTH_S",
        "NEMO_AUTOSCALE_UP",
        "NEMO_AUTOSCALE_DOWN",
        "NEMO_AUTOSCALE_HOLD_UP",
        "NEMO_AUTOSCALE_HOLD_DOWN",
        "NEMO_AUTOSCALE_COOLDOWN_S",
        "NEMO_TRACE",
        "NEMO_SLO_SHED_BUDGET",
    )
    prior_knobs = {k: os.environ.pop(k, None) for k in knobs}
    try:
        with tempfile.TemporaryDirectory(prefix="nemo_obs_fleet_smoke_") as tmp:
            from nemo_tpu.models.synth import SynthSpec, write_corpus
            from nemo_tpu.obs import trace as obs_trace
            from nemo_tpu.obs.promexp import parse_prometheus_text
            from nemo_tpu.service.client import RemoteAnalyzer

            chaos_dir = write_corpus(SynthSpec(n_runs=5, seed=71, name="chaos"), tmp)
            stitch_dir = write_corpus(SynthSpec(n_runs=5, seed=72, name="stitch"), tmp)
            flight_dirs = [os.path.join(tmp, f"flight{i}") for i in range(2)]

            def replica_env(i: int) -> dict:
                env = dict(
                    os.environ,
                    NEMO_LOG_FILE=os.path.join(tmp, f"replica{i}_log.jsonl"),
                    NEMO_CORPUS_CACHE=os.path.join(tmp, f"corpus_cache{i}"),
                    NEMO_RESULT_CACHE=os.path.join(tmp, f"result_cache{i}"),
                    NEMO_JAX_CACHE=os.path.join(tmp, "jax_cache"),
                    # 1 slot, no queue: the shed surge below must reject
                    # instantly (serve.rejected is the autoscaler's up
                    # signal), and capacity=1 keeps the utilization math
                    # legible on the federated page.
                    NEMO_SERVE_INFLIGHT="1",
                    NEMO_SERVE_QUEUE="0",
                    NEMO_FLIGHT_DIR=flight_dirs[i],
                    # One bundle per reason for the whole smoke.
                    NEMO_FLIGHT_COOLDOWN_S="600",
                )
                if i == 0:
                    # First 2 device-lane dispatches fail -> host-lane
                    # failover keeps the request green while the breaker
                    # (threshold 2) trips and fires the flight trigger.
                    env["NEMO_CHAOS"] = "fail_dispatch:2"
                    env["NEMO_BREAKER_FAILURES"] = "2"
                return env

            procs: list = []
            log_fhs: list = []

            def boot(args: list, env: dict, name: str):
                fh = open(os.path.join(tmp, f"{name}.stderr"), "w")
                log_fhs.append(fh)
                p = subprocess.Popen(
                    [_sys.executable, "-m", "nemo_tpu.service.server", *args],
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    env=env,
                )
                procs.append(p)
                return p

            problems: list[str] = []
            ports = PortReservation(3)
            rports = [ports.ports[0], ports.ports[1]]
            router_port = ports.ports[2]
            mport = free_port()
            try:
                replicas = []
                for i in range(2):
                    ports.release(i)
                    replicas.append(
                        boot(
                            ["--port", str(rports[i]), "--platform", "cpu"],
                            replica_env(i),
                            f"replica{i}",
                        )
                    )
                for i in range(2):
                    wait_listening(rports[i], deadline_s=120.0, proc=replicas[i])
                targets = [f"127.0.0.1:{p}" for p in rports]
                for t in targets:
                    with RemoteAnalyzer(target=t) as c:
                        c.wait_ready(60.0)
                ports.release(2)
                router = boot(
                    [
                        "--router",
                        "--port", str(router_port),
                        "--backends", ",".join(targets),
                        "--metrics-port", str(mport),
                    ],
                    dict(
                        os.environ,
                        NEMO_LOG_FILE=os.path.join(tmp, "router_log.jsonl"),
                        NEMO_FLIGHT_DIR=os.path.join(tmp, "flight_router"),
                        # Fast polls + short holds so the hysteresis
                        # round-trips inside a smoke budget: up after 1
                        # shed-delta poll, down after 3 calm polls + 1 s
                        # cooldown.
                        NEMO_ROUTER_HEALTH_S="0.2",
                        NEMO_AUTOSCALE_HOLD_UP="1",
                        NEMO_AUTOSCALE_HOLD_DOWN="3",
                        NEMO_AUTOSCALE_COOLDOWN_S="1",
                    ),
                    "router",
                )
                wait_listening(router_port, deadline_s=60.0, proc=router)
                router_target = f"127.0.0.1:{router_port}"
                with RemoteAnalyzer(target=router_target) as c:
                    c.wait_ready(60.0)

                def replica_counters(t: str) -> dict:
                    with RemoteAnalyzer(target=t) as c:
                        return c.health().get("metrics", {}).get("counters", {})

                def http_json(path: str) -> dict:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}{path}", timeout=15
                    ) as resp:
                        return json.loads(resp.read().decode("utf-8"))

                # ---- 1. Flight recorder: replica 0's first two batched
                # Kernel dispatches hit the injected device-lane faults
                # inside the continuous batcher's scheduler job; failure 2
                # trips the breaker (NEMO_BREAKER_FAILURES=2) and dumps
                # exactly one bundle, then the SAME call succeeds and
                # closes it.
                import numpy as _np

                from nemo_tpu.ingest.molly import load_molly_output
                from nemo_tpu.models.pipeline_model import pack_molly_for_step

                _, kpost, kstatic = pack_molly_for_step(load_molly_output(chaos_dir))
                karrays = {
                    "edge_src": _np.asarray(kpost.edge_src),
                    "edge_dst": _np.asarray(kpost.edge_dst),
                    "edge_mask": _np.asarray(kpost.edge_mask),
                    "is_goal": _np.asarray(kpost.is_goal),
                    "table_id": _np.asarray(kpost.table_id),
                    "node_mask": _np.asarray(kpost.node_mask),
                }
                kparams = {
                    "v": kstatic["v"],
                    "cond_tid": kstatic["post_tid"],
                    "num_tables": kstatic["num_tables"],
                }
                recovered = False
                with RemoteAnalyzer(target=targets[0]) as c:
                    for _ in range(5):
                        try:
                            c.kernel("condition", karrays, kparams)
                        except Exception:
                            continue  # an injected fault surfacing — expected
                        recovered = True
                        break
                if not recovered:
                    problems.append(
                        "replica 0's Kernel RPC never recovered after the "
                        "injected chaos faults were spent"
                    )
                c0 = replica_counters(targets[0])
                if int(c0.get("sched.breaker.trip", 0)) < 1:
                    problems.append(
                        "replica 0 never tripped its breaker under "
                        f"fail_dispatch chaos (counters: "
                        f"{ {k: v for k, v in c0.items() if 'breaker' in k} })"
                    )
                bundles: list = []
                deadline = _time.monotonic() + 30.0
                while _time.monotonic() < deadline:
                    bundles = glob.glob(
                        os.path.join(flight_dirs[0], "flightrec-breaker_trip-*.json")
                    )
                    if bundles:
                        break
                    _time.sleep(0.2)
                if len(bundles) != 1:
                    problems.append(
                        f"expected exactly ONE breaker_trip flight bundle, "
                        f"found {len(bundles)}: {sorted(map(os.path.basename, bundles))}"
                    )
                else:
                    with open(bundles[0], "r", encoding="utf-8") as fh:
                        bundle = json.load(fh)
                    other = bundle.get("otherData", {})
                    if other.get("reason") != "breaker_trip":
                        problems.append(
                            f"flight bundle reason={other.get('reason')!r}, "
                            "want 'breaker_trip'"
                        )
                    if not other.get("context", {}).get("consecutive_failures"):
                        problems.append(
                            "flight bundle context lost the breaker's "
                            "consecutive_failures count"
                        )
                    events = _validate_trace_events(bundle)
                    if not any(ev["ph"] == "X" for ev in events):
                        problems.append(
                            "flight bundle ring captured no spans around the trip"
                        )
                    delta = other.get("metrics_delta", {}).get("counters", {})
                    if int(delta.get("sched.breaker.trip", 0)) < 1:
                        problems.append(
                            "flight bundle metrics_delta does not show the trip"
                        )

                # ---- 2. Trace stitching: warm the corpus through the
                # router, then repeat TRACED — one trace file must hold the
                # client rpc span, the router forward span, and the
                # replica's admission + serve spans, from >=3 processes.
                with RemoteAnalyzer(target=router_target) as c:
                    c.analyze_dir_remote(stitch_dir)  # cold: pins affinity
                trace_path = os.path.join(tmp, "stitched.json")
                obs_trace.start_trace(trace_path)
                try:
                    with RemoteAnalyzer(target=router_target) as c:
                        c.analyze_dir_remote(stitch_dir)  # warm rcache hit
                finally:
                    obs_trace.finish()
                with open(trace_path, "r", encoding="utf-8") as fh:
                    events = _validate_trace_events(json.load(fh))
                names = {ev["name"] for ev in events}
                for want in (
                    "rpc:AnalyzeDir",
                    "router:AnalyzeDir",
                    "serve:admission",
                    "serve:AnalyzeDir",
                ):
                    if want not in names:
                        problems.append(f"stitched trace is missing a {want!r} span")
                pids = {ev["pid"] for ev in events if ev["ph"] == "X"}
                if len(pids) < 3:
                    problems.append(
                        f"stitched trace spans come from {len(pids)} pid(s), "
                        "want >=3 (client + router + replica)"
                    )

                # ---- 3. Federation: the router's /metrics carries both
                # replicas' labeled series, fleet rollups, and liveness.
                text = ""
                deadline = _time.monotonic() + 30.0
                while _time.monotonic() < deadline:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/metrics", timeout=15
                    ) as resp:
                        text = resp.read().decode("utf-8")
                    # Both replica labels appear almost at boot (the first
                    # Health-poll snapshot); the chunks rollup needs a poll
                    # taken AFTER leg 2's analysis, so wait for it too.
                    if (
                        all(f'replica="{t}"' in text for t in targets)
                        and "nemo_fleet_serve_analyze_chunks_total" in text
                    ):
                        break
                    _time.sleep(0.3)
                fams = parse_prometheus_text(text)  # raises on a malformed page
                for t in targets:
                    if f'replica="{t}"' not in text:
                        problems.append(f"/metrics has no series labeled replica={t!r}")
                up_fam = fams.get("nemo_fleet_backend_up", {"samples": []})
                up_vals = {
                    labels.get("replica"): v
                    for _, labels, v in up_fam["samples"]
                }
                if not all(up_vals.get(t) == 1 for t in targets):
                    problems.append(
                        f"nemo_fleet_backend_up does not show both replicas "
                        f"up: {up_vals}"
                    )
                n_up = fams.get("nemo_fleet_backends_up", {"samples": []})["samples"]
                if not n_up or n_up[0][2] != 2:
                    problems.append(f"nemo_fleet_backends_up != 2: {n_up}")
                if "nemo_fleet_serve_analyze_chunks_total" not in fams:
                    problems.append(
                        "/metrics has no summed nemo_fleet_serve_analyze_chunks_total "
                        "counter rollup"
                    )
                cap = {
                    labels.get("agg"): v
                    for _, labels, v in fams.get(
                        "nemo_fleet_serve_capacity", {"samples": []}
                    )["samples"]
                }
                if cap.get("max") != 1 or cap.get("min") != 1:
                    problems.append(
                        f"nemo_fleet_serve_capacity envelope is not the "
                        f"replicas' 1-slot admission capacity: {cap}"
                    )

                # ---- 4. Autoscale: a shed surge (concurrent requests at a
                # full 1-slot/0-queue replica) flips the recommendation up;
                # going idle flips it back down through the hold-count
                # hysteresis.  Warm the surge corpus first (which also
                # proves replica 0 serves normally after the breaker
                # episode) so surge rounds are instant rcache hits.
                with RemoteAnalyzer(target=targets[0]) as c:
                    c.analyze_dir_remote(chaos_dir)
                def surge_round() -> None:
                    def one() -> None:
                        try:
                            with RemoteAnalyzer(target=targets[0]) as c:
                                c.analyze_dir_remote(chaos_dir)  # warm hit
                        except Exception:
                            pass  # the rejections ARE the signal
                    ts = [threading.Thread(target=one) for _ in range(4)]
                    for th in ts:
                        th.start()
                    for th in ts:
                        th.join(timeout=120)

                rec_up = None
                deadline = _time.monotonic() + 90.0
                while _time.monotonic() < deadline:
                    surge_round()
                    _time.sleep(0.3)
                    doc = http_json("/autoscale")
                    if doc.get("recommendation", 0) >= 1:
                        rec_up = doc
                        break
                if rec_up is None:
                    problems.append(
                        "shed surge never flipped /autoscale to a scale-up "
                        f"recommendation (last: {http_json('/autoscale')})"
                    )
                elif rec_up.get("desired_replicas") != 3:
                    problems.append(
                        f"scale-up desired_replicas != live+1: {rec_up}"
                    )
                rec_down = None
                deadline = _time.monotonic() + 90.0
                while _time.monotonic() < deadline:
                    doc = http_json("/autoscale")
                    if doc.get("recommendation", 0) <= -1:
                        rec_down = doc
                        break
                    _time.sleep(0.3)
                if rec_down is None:
                    problems.append(
                        "idle fleet never flipped /autoscale back down "
                        f"(last: {http_json('/autoscale')})"
                    )
                elif rec_down.get("desired_replicas") != 1:
                    problems.append(
                        f"scale-down desired_replicas != max(1, live-1): {rec_down}"
                    )

                # ---- 5. Clean drain of the whole fleet.
                proc_names = ("replica0", "replica1", "router")
                for p in procs:
                    p.send_signal(signal.SIGTERM)
                for name, p in zip(proc_names, procs):
                    try:
                        rc = p.wait(timeout=60)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait(timeout=15)
                        problems.append(f"{name} did not drain inside 60s")
                        continue
                    if rc != 0:
                        problems.append(f"{name} exited rc={rc} after SIGTERM")
            except Exception as ex:
                for name in ("replica0", "replica1", "router"):
                    path = os.path.join(tmp, f"{name}.stderr")
                    if os.path.exists(path):
                        with open(path, "r", encoding="utf-8") as fh:
                            tail = fh.read()[-1500:]
                        if tail.strip():
                            print(
                                f"obs-fleet-smoke: {name} log tail:\n{tail}",
                                file=sys.stderr,
                            )
                print(f"obs-fleet-smoke: {type(ex).__name__}: {ex}", file=sys.stderr)
                return 1
            finally:
                ports.close()
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                        try:
                            p.wait(timeout=15)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait(timeout=15)
                for fh in log_fhs:
                    fh.close()
            if problems:
                print("obs-fleet-smoke: " + "; ".join(problems), file=sys.stderr)
                return 1
            print(
                "obs-fleet-smoke: ok — federated /metrics carried both "
                "replicas' labeled series + fleet rollups, one traced "
                "AnalyzeDir stitched client/router/replica spans into one "
                "trace, an injected breaker trip dumped exactly one flight "
                "bundle (the verb succeeded once the fault budget drained), "
                "a shed surge flipped /autoscale up and idleness "
                "flipped it back down, and the fleet drained clean"
            )
            return 0
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def chaos_smoke() -> int:
    """Fault-tolerance smoke (`make chaos-smoke`, also the tail of `make
    validate`; ISSUE 9) — the chaos harness (utils/chaos.py) injecting
    faults into REAL pipeline runs, asserting the acceptance scenarios:

      (a) **quarantine**: a corpus with 3 corrupted run files completes
          with exactly those runs quarantined (quarantine.json + the
          ingest.quarantined counter), every healthy run analyzed;
      (b) **lane failover + breaker**: injected device-dispatch failures
          complete via host-lane failover with a report byte-identical to
          an uninjected run; repeated failures trip the circuit breaker
          (sched.breaker.*) and a follow-up run executes in degraded
          host-only mode with ZERO failed requests;
      (c) **crash-safe resume**: a SIGKILL mid-sweep (after the first
          segment checkpoint) loses only in-flight work — the rerun maps
          only the unfinished segments (delta.* counters) and produces a
          report byte-identical to an uninterrupted from-scratch run.
    """
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    # Operator knobs must not red (or accidentally green) a healthy
    # validate: the smoke owns every fault-tolerance/chaos/cache knob it
    # exercises for its duration.
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_CHAOS",
            "NEMO_QUARANTINE",
            "NEMO_ANALYSIS_IMPL",
            "NEMO_ANALYSIS_HOST_WORK",
            "NEMO_SCHED",
            "NEMO_MAX_BATCH",
            "NEMO_BREAKER_FAILURES",
            "NEMO_BREAKER_COOLDOWN_S",
            "NEMO_DISPATCH_TIMEOUT_S",
            "NEMO_CHECKPOINT",
            "NEMO_STORE_VERIFY",
            "NEMO_STORE_FINGERPRINT",
            "NEMO_RESULT_CACHE",
            "NEMO_RESULT_CACHE_MAX_GB",
        )
    }
    try:
        return _chaos_smoke_inner()
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def _chaos_smoke_inner() -> int:
    import subprocess

    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, grow_corpus_dir, write_corpus
    from nemo_tpu.parallel import sched as sched_mod
    from nemo_tpu.utils import chaos

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="nemo_chaos_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        os.environ["NEMO_RESULT_CACHE"] = "off"

        # ---------------------------------------------- (a) quarantine
        qdir = write_corpus(SynthSpec(n_runs=8, seed=2, eot=6), os.path.join(tmp, "q"))
        corrupt = {2: "truncate", 3: "garbage", 5: "truncate"}
        for pos, kind in corrupt.items():
            chaos.corrupt_run_file(qdir, pos, kind=kind)
        m0 = obs.metrics.snapshot()
        res = run_debug(qdir, os.path.join(tmp, "q_res"), JaxBackend())
        mq = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
        qf = os.path.join(res.report_dir, "quarantine.json")
        try:
            with open(qf, "r", encoding="utf-8") as fh:
                qdoc = json.load(fh)
        except OSError:
            qdoc = None
        got = sorted(q["position"] for q in qdoc or ())
        if got != sorted(corrupt):
            problems.append(
                f"(a) quarantine.json lists positions {got}, want {sorted(corrupt)}"
            )
        if mq.get("ingest.quarantined") != len(corrupt):
            problems.append(
                f"(a) ingest.quarantined={mq.get('ingest.quarantined')}, want {len(corrupt)}"
            )
        with open(os.path.join(res.report_dir, "debugging.json")) as fh:
            analyzed = {r["iteration"] for r in json.load(fh)}
        want = set(range(8)) - set(corrupt)
        if analyzed != want:
            problems.append(f"(a) analyzed runs {sorted(analyzed)}, want {sorted(want)}")

        # ------------------------------- (b) lane failover + breaker
        fdir = write_corpus(SynthSpec(n_runs=8, seed=3), os.path.join(tmp, "f"))
        fo_env = {
            # Small buckets -> several scheduler jobs; the crossover impl
            # with a floor budget plans them all onto the DEVICE lane even
            # on this CPU box, which is the lane chaos fails.
            "NEMO_ANALYSIS_IMPL": "crossover",
            "NEMO_ANALYSIS_HOST_WORK": "1",
            "NEMO_MAX_BATCH": "2",
            "NEMO_SCHED": "on",
            # Threshold 1: the idle HOST lane steals device-planned jobs
            # faster than the failing device lane can accumulate attempts
            # (work stealing is itself a failover path), so a deterministic
            # trip needs the first failure to count.
            "NEMO_BREAKER_FAILURES": "1",
            "NEMO_BREAKER_COOLDOWN_S": "3600",
        }
        os.environ.update(fo_env)

        def fo_run(label: str):
            chaos.reset()
            sched_mod.reset_session_models()
            m0 = obs.metrics.snapshot()
            r = run_debug(
                fdir, os.path.join(tmp, label), JaxBackend(), corpus_cache="off"
            )
            return (
                _tree(r.report_dir),
                obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"],
            )

        try:
            sched_mod.reset_device_breaker()
            t_ok, _ = fo_run("f_ok")  # uninjected oracle
            os.environ["NEMO_CHAOS"] = "fail_dispatch:4"
            t_inj, m_inj = fo_run("f_inj")
            if not m_inj.get("chaos.injected.fail_dispatch"):
                problems.append("(b) chaos injected no dispatch failures (vacuous)")
            if not m_inj.get("analysis.sched.failover"):
                problems.append(f"(b) no host-lane failover recorded: {m_inj}")
            if not m_inj.get("sched.breaker.trip"):
                problems.append(f"(b) breaker did not trip: {m_inj}")
            if t_inj != t_ok:
                bad = sorted(k for k in t_ok if t_ok.get(k) != t_inj.get(k))
                problems.append(
                    f"(b) failover report diverges from uninjected in {len(bad)} "
                    f"file(s), e.g. {bad[:5]}"
                )
            # Degraded host-only mode: with the breaker OPEN, a fresh run
            # must short-circuit every device plan to the host lane and
            # still succeed (zero failed requests under lane faults).
            os.environ.pop("NEMO_CHAOS", None)
            t_deg, m_deg = fo_run("f_degraded")
            if not m_deg.get("sched.breaker.short_circuit"):
                problems.append(f"(b) open breaker did not short-circuit: {m_deg}")
            if m_deg.get("analysis.route.fused.dense"):
                problems.append(
                    f"(b) degraded mode still dispatched dense fused: {m_deg}"
                )
            if t_deg != t_ok:
                problems.append("(b) degraded host-only report diverges")
        finally:
            for k in fo_env:
                os.environ.pop(k, None)
            os.environ.pop("NEMO_CHAOS", None)
            chaos.reset()
            sched_mod.reset_device_breaker()
            sched_mod.reset_session_models()

        # ------------------------------------ (c) crash-safe resume
        full = write_corpus(SynthSpec(n_runs=12, seed=2, eot=6), os.path.join(tmp, "full"))
        staged = os.path.join(tmp, "staged", os.path.basename(full))
        rc_root = os.path.join(tmp, "rcache")
        os.environ["NEMO_RESULT_CACHE"] = rc_root
        from nemo_tpu.analysis.pipeline import _ingest
        from nemo_tpu.store import resolve_store

        # Build a 3-segment store: populate at 8 runs, append to 10, 12.
        grow_corpus_dir(full, staged, 8)
        store = resolve_store()
        _ingest(staged, True, store)
        for n in (10, 12):
            grow_corpus_dir(full, staged, n)
            store.load_packed(staged)
        header = store._read_header(store.store_dir(staged))
        if len(header["segments"]) != 3:
            problems.append(f"(c) staged store has {len(header['segments'])} segments, want 3")

        # Killed run: a SUBPROCESS (SIGKILL cannot be caught) that dies
        # right after publishing the first segment's checkpoint partial.
        child_env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            NEMO_CHAOS="kill_after_segments:1",
            NEMO_RENDER_WORKERS="1",
        )
        code = (
            "import os\n"
            "from nemo_tpu.analysis.pipeline import run_debug\n"
            "from nemo_tpu.backend.jax_backend import JaxBackend\n"
            f"run_debug({staged!r}, {os.path.join(tmp, 'c_res')!r}, JaxBackend())\n"
            "print('COMPLETED')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], env=child_env,
            capture_output=True, text=True, timeout=600,
        )
        if proc.returncode != -9 or "COMPLETED" in proc.stdout:
            problems.append(
                f"(c) chaos kill did not SIGKILL the sweep (rc={proc.returncode}); "
                f"stderr tail: {proc.stderr[-500:]}"
            )
        # Resume: only the unfinished segments may map.
        m0 = obs.metrics.snapshot()
        r_res = run_debug(staged, os.path.join(tmp, "c_res"), JaxBackend())
        mr = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
        if not mr.get("delta.segments_cached"):
            problems.append(f"(c) resume served no checkpointed segment: {mr}")
        if mr.get("delta.segments_cached", 0) + mr.get("delta.segments_mapped", 0) != 3:
            problems.append(f"(c) resume cached+mapped != 3 segments: {mr}")
        if mr.get("delta.segments_mapped", 0) >= 3:
            problems.append(f"(c) resume re-mapped every segment: {mr}")
        # Byte parity vs an uninterrupted from-scratch run (caches off).
        r_scr = run_debug(
            staged, os.path.join(tmp, "c_scratch"), JaxBackend(),
            corpus_cache="off", result_cache="off",
        )
        t_res, t_scr = _tree(r_res.report_dir), _tree(r_scr.report_dir)
        if t_res != t_scr:
            bad = sorted(k for k in t_scr if t_scr.get(k) != t_res.get(k))
            problems.append(
                f"(c) resumed report diverges from uninterrupted in {len(bad)} "
                f"file(s), e.g. {bad[:5]}"
            )
        os.environ["NEMO_RESULT_CACHE"] = "off"

    if problems:
        print("chaos-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        "chaos-smoke: ok — 3 corrupt runs quarantined with all healthy runs "
        "analyzed; injected device faults completed via host-lane failover "
        "(breaker tripped, degraded host-only run byte-identical, 0 failed "
        "requests); SIGKILL mid-sweep resumed from the checkpointed segment "
        "byte-identical to an uninterrupted run"
    )
    return 0


#: Child harness for the stream smoke / bench stream tier: run one
#: pipeline pass and report wall + RSS watermarks.  Anonymous RSS is
#: sampled from /proc/self/status (RssAnon) on a daemon thread: ru_maxrss
#: counts file-backed mmap pages too — the warm store's shards, touched by
#: BOTH modes' report splice and reclaimable under pressure — which would
#: drown the anonymous working set the streaming bound is actually about.
STREAM_CHILD_CODE = """
import json, os, resource, sys, threading, time

peak = [0]

def _sample():
    while True:
        try:
            with open("/proc/self/status") as fh:
                for line in fh:
                    if line.startswith("RssAnon:"):
                        peak[0] = max(peak[0], int(line.split()[1]))
                        break
        except OSError:
            pass
        time.sleep(0.02)

threading.Thread(target=_sample, daemon=True).start()
from nemo_tpu import obs
from nemo_tpu.analysis.pipeline import run_debug
from nemo_tpu.backend.jax_backend import JaxBackend

t0 = time.perf_counter()
res = run_debug(sys.argv[1], sys.argv[2], JaxBackend(), figures=sys.argv[3])
wall = time.perf_counter() - t0
time.sleep(0.1)  # let the sampler catch the tail
snap = obs.metrics.snapshot()
print("STREAM_CHILD " + json.dumps({
    "wall_s": wall,
    "runs": len(res.molly.runs),
    "peak_rss_mb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
    "anon_peak_mb": peak[0] / 1024.0,
    "stall_s": snap["counters"].get("stream.prefetch_stall_s", 0.0),
    "staged": snap["counters"].get("stream.segments_staged", 0),
    "threaded": int(snap["gauges"].get("stream.threaded", 0)),
    "stage_wall_s": (snap["histograms"].get("stream.stage_s") or {}).get("sum", 0.0),
    "timings": {k: round(v, 4) for k, v in res.timings.items()},
}))
"""


def run_stream_child(
    corpus: str, out_dir: str, figures: str, env: dict, timeout: float = 900.0
) -> dict:
    """Run one STREAM_CHILD_CODE subprocess; returns its report dict."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-c", STREAM_CHILD_CODE, corpus, out_dir, figures],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("STREAM_CHILD "):
            return json.loads(line[len("STREAM_CHILD "):])
    raise RuntimeError(
        f"stream child produced no report (rc={proc.returncode}); "
        f"stderr tail: {proc.stderr[-800:]}"
    )


def stream_smoke() -> int:
    """Out-of-core streaming smoke (`make stream-smoke`, also the tail of
    `make validate`; ISSUE 12): through real pipeline runs over a
    multi-segment store,

      * a streamed run (NEMO_STREAM=on, budget 2) must be byte-identical —
        figures included — to the in-memory oracle (NEMO_STREAM=off), with
        the stream actually staging every segment;
      * over a larger corpus, the streamed run's anonymous-RSS watermark
        (subprocess children identical but for the knob) must sit strictly
        below the in-memory run's — the bounded-working-set contract;
      * a SIGKILL mid-stream must resume via the PR-9 checkpoint path:
        the rerun serves the published segment partials from cache, maps
        only the rest, and reports byte-identical to from-scratch.
    """
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_STREAM",
            "NEMO_STREAM_SEGMENTS",
            "NEMO_STORE_VERIFY",
            "NEMO_STORE_FINGERPRINT",
            "NEMO_STORE_WORKERS",
            "NEMO_RESULT_CACHE",
            "NEMO_RESULT_CACHE_MAX_GB",
            "NEMO_CHECKPOINT",
            "NEMO_CHAOS",
        )
    }
    try:
        return _stream_smoke_inner()
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)


def _stream_smoke_inner() -> int:
    import subprocess

    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus_stream
    from nemo_tpu.store import resolve_store

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="nemo_stream_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        cc = os.path.join(tmp, "corpus_cache")
        os.environ["NEMO_CORPUS_CACHE"] = cc
        os.environ["NEMO_RESULT_CACHE"] = "off"

        # ------------------------- (a) byte parity, figures included
        small = write_corpus_stream(
            SynthSpec(n_runs=24, seed=3, eot=6, name="stream_small"),
            os.path.join(tmp, "small"),
            segment_runs=8,
            store=resolve_store(cc),
        )

        def run(label: str, stream: str, **kw):
            os.environ["NEMO_STREAM"] = stream
            os.environ["NEMO_STREAM_SEGMENTS"] = "2"
            m0 = obs.metrics.snapshot()
            r = run_debug(
                small, os.path.join(tmp, label), JaxBackend(), figures="all", **kw
            )
            return _tree(r.report_dir), obs.Metrics.delta(
                obs.metrics.snapshot(), m0
            )["counters"]

        t_mem, _ = run("a_mem", "off")
        t_str, m_str = run("a_stream", "on")
        if m_str.get("stream.segments_staged", 0) < 3:
            problems.append(
                f"(a) streamed run staged {m_str.get('stream.segments_staged')} "
                "segments (want 3: the run did not actually stream)"
            )
        if t_str != t_mem:
            bad = sorted(k for k in t_mem if t_mem.get(k) != t_str.get(k))
            problems.append(
                f"(a) streamed report diverges from in-memory in {len(bad)} "
                f"file(s), e.g. {bad[:5]}"
            )

        # --------------------- (b) bounded working set (RSS watermark)
        big = write_corpus_stream(
            SynthSpec(n_runs=1600, seed=7, eot=120, name="stream_big"),
            os.path.join(tmp, "big"),
            segment_runs=200,
            store=resolve_store(cc),
        )
        child_env = dict(
            os.environ, JAX_PLATFORMS="cpu", NEMO_STREAM_SEGMENTS="2",
            NEMO_RENDER_WORKERS="1",
        )
        mem = run_stream_child(
            big, os.path.join(tmp, "b_mem"), "sample:4",
            dict(child_env, NEMO_STREAM="off"),
        )
        strm = run_stream_child(
            big, os.path.join(tmp, "b_stream"), "sample:4",
            dict(child_env, NEMO_STREAM="on"),
        )
        if strm["staged"] < 8:
            problems.append(f"(b) streamed child staged {strm['staged']} segments, want 8")
        if not (0 < strm["anon_peak_mb"] < mem["anon_peak_mb"]):
            problems.append(
                f"(b) streamed anon-RSS watermark {strm['anon_peak_mb']:.0f} MB "
                f"not below in-memory {mem['anon_peak_mb']:.0f} MB"
            )

        # ------------------------------ (c) SIGKILL mid-stream resume
        rc_root = os.path.join(tmp, "rcache")
        os.environ["NEMO_RESULT_CACHE"] = rc_root
        os.environ["NEMO_STREAM"] = "on"
        kill_env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            NEMO_CHAOS="kill_after_segments:1", NEMO_RENDER_WORKERS="1",
        )
        code = (
            "from nemo_tpu.analysis.pipeline import run_debug\n"
            "from nemo_tpu.backend.jax_backend import JaxBackend\n"
            f"run_debug({small!r}, {os.path.join(tmp, 'c_res')!r}, JaxBackend())\n"
            "print('COMPLETED')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], env=kill_env,
            capture_output=True, text=True, timeout=600,
        )
        if proc.returncode != -9 or "COMPLETED" in proc.stdout:
            problems.append(
                f"(c) chaos kill did not SIGKILL the stream (rc={proc.returncode}); "
                f"stderr tail: {proc.stderr[-500:]}"
            )
        m0 = obs.metrics.snapshot()
        r_res = run_debug(small, os.path.join(tmp, "c_res"), JaxBackend())
        mr = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
        if not mr.get("delta.segments_cached"):
            problems.append(f"(c) resume served no checkpointed segment: {mr}")
        if mr.get("delta.segments_mapped", 0) >= 3:
            problems.append(f"(c) resume re-mapped every segment: {mr}")
        t_res = _tree(r_res.report_dir)
        if t_res != t_mem:
            bad = sorted(k for k in t_mem if t_mem.get(k) != t_res.get(k))
            problems.append(
                f"(c) resumed streamed report diverges in {len(bad)} file(s), "
                f"e.g. {bad[:5]}"
            )
        os.environ["NEMO_RESULT_CACHE"] = "off"

    if problems:
        print("stream-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        "stream-smoke: ok — streamed run byte-identical to the in-memory "
        "oracle (figures included); anonymous-RSS watermark "
        f"{strm['anon_peak_mb']:.0f} MB streamed vs {mem['anon_peak_mb']:.0f} MB "
        "in-memory over 8 segments; SIGKILL mid-stream resumed from the "
        "checkpointed partials byte-identical to from-scratch"
    )
    return 0


def synth_smoke() -> int:
    """Batched correction/extension synthesis smoke (`make synth-smoke`,
    also the tail of `make validate`; ISSUE 13):

      * forced NEMO_SYNTH_IMPL=python / sparse / sparse_device pipeline
        runs must produce byte-identical repair trees (repairs.json and
        the whole report), each with its analysis.route.synth.<route>
        record;
      * the corpus-wide ranking must be stable under segment permutation
        (reducing the cached partials in any order ranks identically);
      * a streamed 3-segment run must produce the same ranked list as the
        in-memory sweep;
      * the batched synthesis phase must be >=5x faster than the per-run
        Python oracle (the acceptance floor, enforced here at smoke
        scale; bench synth_tier measures it at 1x and 10.2k).
    """
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_SYNTH_IMPL",
            "NEMO_SYNTH_HOST_WORK",
            "NEMO_STREAM",
            "NEMO_STREAM_SEGMENTS",
            "NEMO_RESULT_CACHE",
            "NEMO_ANALYSIS_IMPL",
        )
    }
    try:
        return _synth_smoke_inner()
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)


def _synth_smoke_inner() -> int:
    import time

    from nemo_tpu import obs
    from nemo_tpu.analysis import delta
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.analysis.synth import build_repairs
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus, write_corpus_stream
    from nemo_tpu.store import resolve_store
    from nemo_tpu.store.rcache import resolve_result_cache

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="nemo_synth_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        cc = os.path.join(tmp, "corpus_cache")
        os.environ["NEMO_CORPUS_CACHE"] = cc
        os.environ["NEMO_RESULT_CACHE"] = "off"

        # ---------------- (a) forced-route byte parity + route records
        corpus = write_corpus(SynthSpec(n_runs=10, seed=3, eot=6), tmp)
        trees: dict[str, dict[str, bytes]] = {}
        for impl in ("python", "sparse", "sparse_device"):
            os.environ["NEMO_SYNTH_IMPL"] = impl
            m0 = obs.metrics.snapshot()
            r = run_debug(
                corpus, os.path.join(tmp, f"route_{impl}"), JaxBackend(),
                figures="none",
            )
            mc = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            if not mc.get(f"analysis.route.synth.{impl}"):
                problems.append(
                    f"(a) NEMO_SYNTH_IMPL={impl} recorded no "
                    f"analysis.route.synth.{impl}: "
                    f"{ {k: v for k, v in mc.items() if k.startswith('analysis.route.synth')} }"
                )
            trees[impl] = _tree(r.report_dir)
            if "repairs.json" not in trees[impl]:
                problems.append(f"(a) {impl} run produced no repairs.json")
        os.environ.pop("NEMO_SYNTH_IMPL", None)
        for impl in ("sparse", "sparse_device"):
            if trees[impl].keys() != trees["python"].keys():
                problems.append(
                    f"(a) {impl} report file set DIVERGES from the oracle: "
                    f"{sorted(trees[impl].keys() ^ trees['python'].keys())[:5]}"
                )
                continue
            bad = sorted(
                k
                for k in trees["python"]
                if trees["python"][k] != trees[impl][k]
            )
            if bad:
                problems.append(
                    f"(a) {impl} repair tree DIVERGES from the per-run oracle "
                    f"in {len(bad)} file(s), e.g. {bad[:5]}"
                )

        # -------- (b) streamed 3-segment == in-memory, permutation-stable
        seg_corpus = write_corpus_stream(
            SynthSpec(n_runs=24, seed=5, eot=6, name="synth_seg"),
            os.path.join(tmp, "seg"),
            segment_runs=8,
            store=resolve_store(cc),
        )
        rc_root = os.path.join(tmp, "rcache")
        os.environ["NEMO_RESULT_CACHE"] = rc_root
        os.environ["NEMO_STREAM"] = "off"
        r_mem = run_debug(
            seg_corpus, os.path.join(tmp, "b_mem"), JaxBackend(), figures="none",
            corpus_cache=cc, result_cache=rc_root,
        )
        t_mem = _tree(r_mem.report_dir)
        os.environ["NEMO_STREAM"] = "on"
        os.environ["NEMO_STREAM_SEGMENTS"] = "2"
        r_str = run_debug(
            seg_corpus, os.path.join(tmp, "b_stream"), JaxBackend(), figures="none",
            corpus_cache=cc, result_cache="off",
        )
        t_str = _tree(r_str.report_dir)
        if t_str.get("repairs.json") != t_mem.get("repairs.json"):
            problems.append("(b) streamed ranked repair list diverges from in-memory")
        if t_str != t_mem:
            bad = sorted(k for k in t_mem if t_mem.get(k) != t_str.get(k))
            problems.append(
                f"(b) streamed report diverges from in-memory in {len(bad)} "
                f"file(s), e.g. {bad[:5]}"
            )
        os.environ["NEMO_STREAM"] = "off"

        # Permutation stability: reduce the CACHED partials (populated by
        # the in-memory run above) forward and reversed — the ranked
        # document must be byte-identical either way.
        molly = r_mem.molly
        good = delta.choose_good_run(molly)
        baseline = delta.choose_baseline_run(molly, good)
        segments = delta.attach_positions(delta.corpus_segments(molly), molly)
        rcache = resolve_result_cache(rc_root)
        parts = []
        for seg in segments:
            key = delta.partial_cache_key(seg, segments, good, baseline, "none")
            p = rcache.load_partial(key) if key else None
            if p is not None:
                parts.append(p)
        if len(parts) != 3:
            problems.append(
                f"(b) expected 3 cached segment partials, loaded {len(parts)}"
            )
        else:
            docs = []
            for order in (parts, parts[::-1], [parts[1], parts[2], parts[0]]):
                red = delta.reduce_partials(list(order), molly, good)
                docs.append(json.dumps(red.repairs, sort_keys=True))
            if len(set(docs)) != 1:
                problems.append("(b) ranking changed under segment permutation")

        # ---------------- (c) batched >=5x over the per-run oracle
        os.environ["NEMO_RESULT_CACHE"] = "off"
        # eot=40 deep chains: per-run PGraph construction (the oracle's
        # real cost) scales with graph size while the batched scatters
        # amortize — measured ~38x here, comfortably above the 5x floor.
        perf_corpus = write_corpus(
            SynthSpec(n_runs=600, seed=9, eot=40, name="synth_perf"),
            os.path.join(tmp, "perf"),
        )
        from nemo_tpu.analysis.pipeline import _ingest

        be = JaxBackend()
        molly_p = _ingest(perf_corpus, True, resolve_store(cc))
        be.init_graph_db("", molly_p)
        be.load_raw_provenance()
        all_iters = molly_p.get_runs_iters()
        be._synth_impl = "python"
        t0 = time.perf_counter()
        oracle = be.synth_candidates(all_iters)
        oracle_s = time.perf_counter() - t0
        be._synth_impl = "sparse"
        t0 = time.perf_counter()
        batched = be.synth_candidates(all_iters)
        batched_s = time.perf_counter() - t0
        be.close_db()
        if batched != oracle:
            diverging = [i for i in all_iters if batched.get(i) != oracle.get(i)][:5]
            problems.append(
                f"(c) batched candidates diverge from the oracle, e.g. runs "
                f"{diverging}"
            )
        if oracle_s < batched_s * 5:
            problems.append(
                f"(c) batched synthesis only {oracle_s / max(batched_s, 1e-9):.1f}x "
                f"faster than the per-run oracle over {len(all_iters)} runs "
                f"({batched_s:.3f}s vs {oracle_s:.3f}s; want >=5x)"
            )

    if problems:
        print("synth-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        "synth-smoke: ok — python/sparse/sparse_device repair trees "
        "byte-identical with routes recorded; streamed 3-segment ranking == "
        "in-memory and permutation-stable; batched synthesis "
        f"{oracle_s / max(batched_s, 1e-9):.0f}x over the per-run oracle "
        f"({len(all_iters)} runs)"
    )
    return 0


def watch_smoke() -> int:
    """Live-watch smoke (`make watch-smoke`, also the tail of `make
    validate`; ISSUE 15): the replay driver feeds a 3-generation sweep
    into a LIVE watcher with one AnalyzeDirStream subscriber, asserting

      * >= 3 `report_update` events arrive in generation order (run counts
        strictly increasing);
      * every update cycle is O(new runs): `runs_mapped` == the cycle's
        new runs (zero re-dispatch of already-cached segments, whose
        count grows 0 -> 1 -> 2 across the updates);
      * the watcher's FINAL published report is byte-identical to a
        post-hoc one-shot run of the full corpus;
      * a mid-sweep TRUNCATED provenance file is quarantined (degraded
        report, sweep continues) and picked up on repair via the store's
        GROWN re-ingest, mapping ONLY the repaired run — no full
        re-analysis.
    """
    import importlib.util

    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_STORE_VERIFY",
            "NEMO_STORE_FINGERPRINT",
            "NEMO_RESULT_CACHE",
            "NEMO_RESULT_CACHE_MAX_GB",
            "NEMO_WATCH_POLL_S",
            "NEMO_WATCH_DEBOUNCE_S",
            "NEMO_INJECTOR",
        )
    }
    try:
        return _watch_smoke_inner(importlib.util.find_spec("grpc") is not None)
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def _watch_smoke_inner(have_grpc: bool) -> int:
    import shutil
    import threading

    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, grow_corpus_dir, write_corpus
    from nemo_tpu.watch import WatchConfig, Watcher, start_replay

    problems: list[str] = []
    with tempfile.TemporaryDirectory(prefix="nemo_watch_smoke_") as tmp:
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        os.environ["NEMO_RESULT_CACHE"] = os.path.join(tmp, "result_cache")
        full = write_corpus(
            SynthSpec(n_runs=9, seed=11, name="sweep"), os.path.join(tmp, "full")
        )
        live_dir = os.path.join(tmp, "live", "sweep")
        os.makedirs(live_dir)
        wres = os.path.join(tmp, "wres")
        watch_opts = {
            "results_root": wres,
            "max_updates": 3,
            "poll_s": 0.1,
            "debounce_s": 0.1,
            "figures": "failed",
        }

        # ---- 1. Replay-driven live session with one AnalyzeDirStream
        # subscriber.  grpc-less environments run the watcher in-process
        # (the subscriber queue IS the event stream — same event payloads);
        # with grpc the events flow through a real sidecar stream.
        events: list[dict] = []
        if have_grpc:
            from nemo_tpu.service.client import RemoteAnalyzer
            from nemo_tpu.service.server import make_server

            server, port = make_server(port=0)
            server.start()
            try:
                th, rstop = start_replay(
                    full, live_dir, generations=3, interval_s=2.0
                )
                with RemoteAnalyzer(target=f"127.0.0.1:{port}") as c:
                    for ev in c.analyze_dir_stream([live_dir], watch=watch_opts):
                        events.append(ev)
                rstop.set()
            finally:
                server.stop(None)
        else:
            print(
                "watch-smoke: grpcio not installed; driving the watcher "
                "in-process (the stream leg is skipped)",
                file=sys.stderr,
            )
            w = Watcher(
                live_dir,
                wres,
                JaxBackend,
                WatchConfig(poll_s=0.1, debounce_s=0.1, max_updates=3,
                            figures="failed"),
            )
            q = w.subscribe()
            th, rstop = start_replay(full, live_dir, generations=3, interval_s=2.0)
            w.run()
            rstop.set()
            while not q.empty():
                events.append(q.get())

        ups = [e for e in events if e.get("event") == "report_update"]
        if len(ups) < 3:
            problems.append(
                f"expected >=3 report_update events, got {len(ups)} "
                f"(events: {[e.get('event') for e in events]})"
            )
        else:
            totals = [e["runs_total"] for e in ups]
            if totals != sorted(totals) or len(set(totals)) != len(totals):
                problems.append(
                    f"updates not in generation order: runs_total={totals}"
                )
            if totals and totals[-1] != 9:
                problems.append(
                    f"final update covers {totals[-1]} runs, want 9"
                )
            for k, e in enumerate(ups):
                if e["runs_mapped"] != e["new_runs"]:
                    problems.append(
                        f"update {k + 1} mapped {e['runs_mapped']} runs for "
                        f"{e['new_runs']} new ones — cached segments were "
                        "re-dispatched"
                    )
            cached = [e["segments_cached"] for e in ups[:3]]
            if cached != [0, 1, 2]:
                problems.append(
                    f"cached-segment counts {cached} (want [0, 1, 2]: every "
                    "already-analyzed segment must serve from the partial tier)"
                )

        # ---- 2. Final published report byte-identical to a post-hoc
        # one-shot of the full corpus (fresh caches: full recompute).
        live_report = os.path.join(wres, "sweep")
        if not os.path.isdir(live_report):
            problems.append(f"no live report published at {live_report}")
        else:
            one = run_debug(
                live_dir,
                os.path.join(tmp, "oneshot"),
                JaxBackend(),
                figures="failed",
                report_name="sweep",
                corpus_cache=os.path.join(tmp, "cc2"),
                result_cache="off",
            )
            t_live, t_one = _tree(live_report), _tree(one.report_dir)
            if t_live.keys() != t_one.keys():
                problems.append(
                    "live/post-hoc report file sets diverge: "
                    f"{sorted(t_live.keys() ^ t_one.keys())[:5]}"
                )
            else:
                bad = sorted(k for k in t_one if t_one[k] != t_live[k])
                if bad:
                    problems.append(
                        f"final live report DIVERGES from the post-hoc "
                        f"one-shot in {len(bad)} file(s), e.g. {bad[:5]}"
                    )

        # ---- 3. Mid-write quarantine -> repair-GROWN pickup, O(repair).
        qsweep = os.path.join(tmp, "qsweep", "sweep")
        grow_corpus_dir(full, qsweep, 4)
        victim = os.path.join(qsweep, "run_3_post_provenance.json")
        intact = open(victim, "rb").read()
        with open(victim, "wb") as fh:
            fh.write(intact[: len(intact) // 2])  # a half-written flush
        w2 = Watcher(
            qsweep,
            os.path.join(tmp, "qres"),
            JaxBackend,
            WatchConfig(poll_s=0.1, debounce_s=0.1, max_updates=2,
                        figures="none"),
        )
        q2 = w2.subscribe()
        wt = threading.Thread(target=w2.run, daemon=True)
        wt.start()
        try:
            ev1 = q2.get(timeout=60)
            if ev1.get("quarantined") != 1 or ev1.get("runs_total") != 3:
                problems.append(
                    f"truncated run not quarantined: {ev1.get('quarantined')} "
                    f"quarantined / {ev1.get('runs_total')} analyzed (want 1/3)"
                )
            with open(victim, "wb") as fh:  # the injector finishes the file
                fh.write(intact)
            ev2 = q2.get(timeout=60)
            if ev2.get("runs_total") != 4 or ev2.get("quarantined") != 0:
                problems.append(
                    f"repaired run not picked up: runs_total="
                    f"{ev2.get('runs_total')} quarantined={ev2.get('quarantined')}"
                )
            if ev2.get("runs_mapped") != 1:
                problems.append(
                    f"repair cycle mapped {ev2.get('runs_mapped')} runs "
                    "(want 1: the repaired run only, not a re-analysis)"
                )
        except Exception as ex:
            problems.append(
                f"quarantine/repair leg failed: {type(ex).__name__}: {ex}"
            )
        finally:
            w2.stop()
            wt.join(timeout=30)
        shutil.rmtree(os.path.join(tmp, "qres"), ignore_errors=True)

    if problems:
        print("watch-smoke: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(
        "watch-smoke: ok — 3 replay generations produced 3 in-order "
        "report_update events, each cycle mapped only its new runs "
        "(cached segments 0/1/2 served from the partial tier), the final "
        "live report is byte-identical to the post-hoc one-shot, and a "
        "mid-write truncated file was quarantined then picked up on "
        "repair by mapping exactly 1 run"
    )
    return 0


#: Child harness for the profile smoke: one CLI-shaped pipeline run in a
#: fresh process (the calibration trigger is the backend's init_graph_db),
#: reporting the profile.* counters, the per-constant sources, and the
#: report dir for the parent's byte-parity compare.
PROFILE_CHILD_CODE = """
import json, sys

from nemo_tpu import obs
from nemo_tpu.analysis.pipeline import run_debug
from nemo_tpu.backend.jax_backend import JaxBackend

res = run_debug(sys.argv[1], sys.argv[2], JaxBackend(), figures="all")
from nemo_tpu.platform import profile as pp

snap = obs.metrics.snapshot()
c, g = snap["counters"], snap["gauges"]
print("PROFILE_CHILD " + json.dumps({
    "report_dir": res.report_dir,
    "calibrated": c.get("profile.calibrated", 0),
    "loaded": c.get("profile.loaded", 0),
    "probes": c.get("profile.probe.dispatches", 0),
    "stale": c.get("profile.stale", 0),
    "calibration_s": g.get("profile.calibration_s", 0.0),
    "sources": {r["name"]: r["source"] for r in pp.constant_sources()},
}))
"""

#: Every env knob that feeds routing-constant resolution: stripped from the
#: profile children so ONLY the scenario's explicit settings decide
#: precedence (the operator's shell must not leak into the matrix).
PROFILE_ROUTING_KNOBS = (
    "NEMO_PROFILE", "NEMO_PROFILE_DIR", "NEMO_PROFILE_BUDGET_S",
    "NEMO_ANALYSIS_HOST_WORK", "NEMO_SYNTH_HOST_WORK", "NEMO_DIFF_HOST_WORK",
    "NEMO_SPARSE_DEVICE_MEM_MB", "NEMO_SPARSE_DEVICE_DENSITY",
    "NEMO_SPARSE_DEVICE_MIN_V",
    "NEMO_SCHED_HOST_UNIT", "NEMO_SCHED_DEVICE_UNIT",
    "NEMO_SCHED_SPARSE_DEVICE_UNIT", "NEMO_SCHED_DEVICE_FIXED",
    "NEMO_SCHED_FLOPS_PER_S", "NEMO_ANALYSIS_IMPL", "NEMO_SYNTH_IMPL",
)


def profile_smoke() -> int:
    """Platform-profile smoke (`make profile-smoke`, also the tail of
    `make validate`; ISSUE 19): against one synthetic corpus and one
    hermetic profile dir, four fresh processes prove the calibration
    lifecycle end to end —

      cold    NEMO_PROFILE=auto, empty profile dir: exactly ONE bounded
              calibration (< 10 s wall) persists a fingerprint-keyed file
      warm    same dir, second process: boots measured with ZERO probe
              dispatches and zero calibrations
      off     NEMO_PROFILE=off: no load, no probes — the pre-profile
              resolution, bit-for-bit
      forced  profile active but env overrides pin routing constants:
              env wins (sources say so) with zero probes

    and all four report trees are byte-identical — measured routing
    changes WHERE work runs, never what the report says (the lane
    bit-identity contract)."""
    import glob
    import subprocess

    from nemo_tpu.models.synth import SynthSpec, write_corpus

    with tempfile.TemporaryDirectory(prefix="nemo_profile_smoke_") as tmp:
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)
        prof_dir = os.path.join(tmp, "plat")

        def run_child(name: str, **overrides) -> dict:
            env = os.environ.copy()
            for k in PROFILE_ROUTING_KNOBS:
                env.pop(k, None)
            env.update(
                JAX_PLATFORMS="cpu",
                NEMO_PROFILE_DIR=prof_dir,
                NEMO_SVG_CACHE=os.path.join(tmp, "svg"),
                NEMO_CORPUS_CACHE=os.path.join(tmp, "corpus_cache"),
                NEMO_RESULT_CACHE="off",
                NEMO_RENDER_WORKERS="1",
            )
            env.update(overrides)
            proc = subprocess.run(
                [sys.executable, "-c", PROFILE_CHILD_CODE, corpus,
                 os.path.join(tmp, name)],
                env=env, capture_output=True, text=True, timeout=600,
            )
            for line in proc.stdout.splitlines():
                if line.startswith("PROFILE_CHILD "):
                    return json.loads(line[len("PROFILE_CHILD "):])
            raise RuntimeError(
                f"profile child {name!r} produced no report "
                f"(rc={proc.returncode}); stderr tail: {proc.stderr[-800:]}"
            )

        cold = run_child("cold", NEMO_PROFILE="auto")
        if cold["calibrated"] != 1 or not cold["probes"]:
            print(
                "profile-smoke: cold root did not calibrate exactly once "
                f"with probe dispatches: {cold}",
                file=sys.stderr,
            )
            return 1
        if not 0 < cold["calibration_s"] < 10.0:
            print(
                f"profile-smoke: calibration wall {cold['calibration_s']:.2f}s "
                "outside the (0, 10s) bound",
                file=sys.stderr,
            )
            return 1
        files = glob.glob(os.path.join(prof_dir, "profile-*.json"))
        if len(files) != 1:
            print(
                f"profile-smoke: expected ONE fingerprint-keyed profile file, "
                f"found {files}",
                file=sys.stderr,
            )
            return 1

        warm = run_child("warm", NEMO_PROFILE="auto")
        if warm["calibrated"] or warm["probes"] or warm["loaded"] != 1:
            print(
                "profile-smoke: second process did not boot measured with "
                f"zero probes: {warm}",
                file=sys.stderr,
            )
            return 1
        if warm["sources"]["analysis_host_work"] != "measured":
            print(
                f"profile-smoke: warm boot resolved sources {warm['sources']}, "
                "expected analysis_host_work=measured",
                file=sys.stderr,
            )
            return 1

        off = run_child("off", NEMO_PROFILE="off")
        if off["calibrated"] or off["probes"] or off["loaded"]:
            print(
                f"profile-smoke: NEMO_PROFILE=off still touched the profile: {off}",
                file=sys.stderr,
            )
            return 1
        if any(s != "seeded" for s in off["sources"].values()):
            print(
                f"profile-smoke: profile-off sources not all seeded: {off['sources']}",
                file=sys.stderr,
            )
            return 1

        forced = run_child(
            "forced",
            NEMO_PROFILE="auto",
            NEMO_ANALYSIS_HOST_WORK="50000",
            NEMO_SCHED_FLOPS_PER_S="5e9",
        )
        if forced["probes"] or forced["loaded"] != 1:
            print(
                f"profile-smoke: env-forced run re-probed or failed to load: {forced}",
                file=sys.stderr,
            )
            return 1
        if (
            forced["sources"]["analysis_host_work"] != "env"
            or forced["sources"]["sched_flops_per_s"] != "env"
        ):
            print(
                "profile-smoke: env overrides did not win the precedence: "
                f"{forced['sources']}",
                file=sys.stderr,
            )
            return 1

        trees = {
            name: _tree(rep["report_dir"])
            for name, rep in (
                ("cold", cold), ("warm", warm), ("off", off), ("forced", forced)
            )
        }
        base = trees["cold"]
        for name, tree in trees.items():
            if tree.keys() != base.keys():
                print(
                    f"profile-smoke: {name} report file set DIVERGES from cold: "
                    f"{sorted(tree.keys() ^ base.keys())[:10]}",
                    file=sys.stderr,
                )
                return 1
            bad = sorted(k for k in base if tree[k] != base[k])
            if bad:
                print(
                    f"profile-smoke: {name} report DIVERGES from the cold run "
                    f"in {len(bad)} file(s), e.g. {bad[:5]} — measured routing "
                    "must never change report bytes",
                    file=sys.stderr,
                )
                return 1

    print(
        "profile-smoke: ok — cold root calibrated once "
        f"({cold['calibration_s']:.2f}s, {cold['probes']} probe dispatches, "
        "one fingerprint-keyed file), warm boot measured with zero probes, "
        "env overrides win with the measurement preserved, and report "
        "trees are byte-identical across profile-on/off/env-forced"
    )
    return 0


def query_smoke() -> int:
    """Ad-hoc query-engine smoke (`make query-smoke`, also the tail of
    `make validate`; ISSUE 20):

      * every fixed analysis verb in query/verbs.py:VERB_QUERIES, executed
        as its query-layer program, is BYTE-identical to the native verb's
        per-run result (two independently-derived documents);
      * a novel 3-pattern query compiles cold (plan + execute, kernel
        dispatches > 0) and its warm repeat is a full-result rcache hit
        with ZERO kernel dispatches, document-identical;
      * the sidecar's JSON-carried Query RPC round-trips the same document
        and a malformed query is INVALID_ARGUMENT, not a crash.
    """
    import importlib.util

    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")
    prior_knobs = {
        k: os.environ.pop(k, None)
        for k in (
            "NEMO_CORPUS_CACHE",
            "NEMO_RESULT_CACHE",
            "NEMO_STORE_FINGERPRINT",
            "NEMO_INJECTOR",
            "NEMO_ANALYSIS_IMPL",
        )
    }
    try:
        return _query_smoke_inner(importlib.util.find_spec("grpc") is not None)
    finally:
        for k, v in prior_knobs.items():
            if v is not None:
                os.environ[k] = v


def _query_smoke_inner(have_grpc: bool) -> int:
    from nemo_tpu import obs
    from nemo_tpu.analysis.pipeline import _ingest
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.query import run_query_text
    from nemo_tpu.query.verbs import VERB_QUERIES, native_verb_result, run_verb
    from nemo_tpu.store import resolve_store

    with tempfile.TemporaryDirectory(prefix="nemo_query_smoke_") as tmp:
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        os.environ["NEMO_RESULT_CACHE"] = os.path.join(tmp, "result_cache")
        corpus = write_corpus(SynthSpec(n_runs=8, seed=2, eot=6), tmp)
        molly = _ingest(corpus, use_packed=True, store=resolve_store())

        # 1. Fixed verbs as query-layer programs: byte parity per verb
        # against the native verb path (backend kernels / host oracles).
        backend = JaxBackend()
        backend.init_graph_db("", molly)
        for name in VERB_QUERIES:
            got = run_verb(name, molly, use_cache=False)["runs"]
            want = native_verb_result(name, backend)
            if json.dumps(got, sort_keys=True).encode() != json.dumps(
                want, sort_keys=True
            ).encode():
                print(
                    f"query-smoke: verb {name!r} as a query DIVERGES from "
                    f"the native verb: query={got} native={want}",
                    file=sys.stderr,
                )
                return 1

        # 2. Novel 3-pattern query: cold = plan + execute with kernel
        # dispatches; warm = full-result cache hit with zero dispatches.
        text = (
            "from pre match goal[holds=true] -> @rule "
            "match goal[holds=false] -*-> @rule[type=async] "
            "match @goal[table=pre] count by table"
        )

        def run_once():
            m0 = obs.metrics.snapshot()
            doc = run_query_text(text, molly)
            mc = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            disp = sum(
                v for k, v in mc.items() if k.startswith("kernel.dispatches.")
            )
            return doc, mc, disp

        cold, _mc_cold, disp_cold = run_once()
        if cold["stats"]["cache"] != "miss" or disp_cold == 0:
            print(
                "query-smoke: cold query expected a cache miss with kernel "
                f"dispatches, got stats={cold['stats']} dispatches={disp_cold}",
                file=sys.stderr,
            )
            return 1
        warm, mc_warm, disp_warm = run_once()
        if (
            warm["stats"]["cache"] != "hit"
            or disp_warm != 0
            or not mc_warm.get("query.cache.hit")
        ):
            print(
                "query-smoke: warm repeat expected a zero-dispatch full-result "
                f"cache hit, got stats={warm['stats']} dispatches={disp_warm} "
                f"counters={ {k: v for k, v in mc_warm.items() if k.startswith('query.')} }",
                file=sys.stderr,
            )
            return 1
        strip = lambda d: {k: v for k, v in d.items() if k != "stats"}  # noqa: E731
        if strip(warm) != strip(cold):
            print("query-smoke: warm document DIVERGES from cold", file=sys.stderr)
            return 1

        # 3. Sidecar Query RPC round-trip (JSON-carried, protoc-free).
        if have_grpc:
            import grpc

            from nemo_tpu.service.client import RemoteAnalyzer
            from nemo_tpu.service.server import make_server

            server, port = make_server(port=0)
            server.start()
            try:
                with RemoteAnalyzer(target=f"localhost:{port}") as c:
                    remote = c.query_remote(corpus, text)
                    if strip(remote) != strip(cold):
                        print(
                            "query-smoke: sidecar Query document DIVERGES "
                            f"from local: remote={strip(remote)}",
                            file=sys.stderr,
                        )
                        return 1
                    try:
                        c.query_remote(corpus, "from nowhere tables")
                    except grpc.RpcError as ex:
                        if ex.code() != grpc.StatusCode.INVALID_ARGUMENT:
                            print(
                                "query-smoke: malformed query expected "
                                f"INVALID_ARGUMENT, got {ex.code()}",
                                file=sys.stderr,
                            )
                            return 1
                    else:
                        print(
                            "query-smoke: malformed query did not error",
                            file=sys.stderr,
                        )
                        return 1
            finally:
                server.stop(None)
        print(
            "query-smoke: ok — "
            f"{len(VERB_QUERIES)} fixed verbs byte-identical as query "
            f"programs, novel 3-pattern query cold ({disp_cold} kernel "
            "dispatches) -> warm full-result cache hit with 0 dispatches"
            + (
                ", sidecar Query RPC round-trip identical"
                if have_grpc
                else " (grpc unavailable: RPC leg skipped)"
            )
        )
    return 0


def main() -> int:
    from nemo_tpu.analysis.pipeline import run_debug
    from nemo_tpu.backend.jax_backend import JaxBackend
    from nemo_tpu.backend.python_ref import PythonBackend
    from nemo_tpu.models.synth import SynthSpec, write_corpus
    from nemo_tpu.report.writer import Reporter
    from nemo_tpu.utils.jax_config import pin_platform

    pin_platform("cpu")  # never touch a (possibly tunneled) device here
    with tempfile.TemporaryDirectory(prefix="nemo_validate_") as tmp:
        # Hermetic SVG + corpus caches: cold for the first pass, warm for
        # the second, never the user's ~/.cache.  (The corpus store warms
        # across the passes below, so the parity steps double as a
        # store-on byte-parity check; the dedicated legs live in
        # store_smoke.)
        os.environ["NEMO_SVG_CACHE"] = os.path.join(tmp, "svg_cache")
        os.environ["NEMO_CORPUS_CACHE"] = os.path.join(tmp, "corpus_cache")
        # The result cache is OFF here: these steps assert that renders and
        # kernel dispatches actually happen (SVG-warm stats, forced-route
        # counters) — a report-cache hit would short-circuit them all.  The
        # dedicated delta smoke covers the result cache.
        os.environ["NEMO_RESULT_CACHE"] = "off"
        os.environ.pop("NEMO_RENDER_WORKERS", None)
        corpus = write_corpus(SynthSpec(n_runs=6, seed=3), tmp)

        # 1. Render-pipeline parity: pipeline (dedup+cache+workers) vs the
        # sequential per-figure oracle, same backend, full figure set.
        jx = run_debug(corpus, os.path.join(tmp, "jx"), JaxBackend(), figures="all")
        seq = run_debug(
            corpus,
            os.path.join(tmp, "seq"),
            JaxBackend(),
            reporter=Reporter(),  # no scheduler: the sequential oracle path
            figures="all",
        )
        a, b = _tree(jx.report_dir), _tree(seq.report_dir)
        if a.keys() != b.keys():
            print(
                "validate: report file sets DIVERGE: "
                f"{sorted(a.keys() ^ b.keys())[:10]}",
                file=sys.stderr,
            )
            return 1
        bad = sorted(k for k in a if a[k] != b[k])
        if bad:
            print(
                "validate: pipeline-rendered report DIVERGES from the "
                f"sequential renderer in {len(bad)} file(s), e.g. {bad[:5]}",
                file=sys.stderr,
            )
            return 1

        # 2. Cache-warm re-report: zero renders, identical bytes.
        jx2 = run_debug(corpus, os.path.join(tmp, "jx2"), JaxBackend(), figures="all")
        s = jx2.figure_stats or {}
        if s.get("rendered") != 0 or s.get("figure_cache_hits") != s.get("unique_figures"):
            print(f"validate: SVG cache not warm on the second pass: {s}", file=sys.stderr)
            return 1
        warm = _tree(jx2.report_dir)
        bad2 = sorted(k for k in a if warm.get(k) != a[k])
        if bad2:
            print(
                f"validate: cache-warm report DIVERGES in {len(bad2)} file(s), "
                f"e.g. {bad2[:5]}",
                file=sys.stderr,
            )
            return 1

        # 3. Backend analysis parity: jax debugging.json == oracle's.
        py = run_debug(
            corpus, os.path.join(tmp, "py"), PythonBackend(), figures="none"
        )
        with open(os.path.join(jx.report_dir, "debugging.json")) as f:
            dbg_jx = json.load(f)
        with open(os.path.join(py.report_dir, "debugging.json")) as f:
            dbg_py = json.load(f)
        if dbg_jx != dbg_py:
            print("validate: jax report DIVERGES from the oracle", file=sys.stderr)
            return 1

        # 4. Analysis-route crossover (ISSUE 3): each forced route must
        # record an analysis.route decision for EVERY verb this smoke
        # dispatches (fused + diff — the corpus has failed runs), and the
        # two routes' full report trees must be byte-identical: the sparse
        # CSR host engine is a drop-in for the dense dispatch end to end.
        from nemo_tpu import obs

        route_trees: dict[str, dict[str, bytes]] = {}
        prior_impl = os.environ.get("NEMO_ANALYSIS_IMPL")
        for impl in ("sparse", "dense"):
            os.environ["NEMO_ANALYSIS_IMPL"] = impl
            try:
                m0 = obs.metrics.snapshot()
                r = run_debug(
                    corpus, os.path.join(tmp, f"route_{impl}"), JaxBackend(),
                    figures="all",
                )
                mc = obs.Metrics.delta(obs.metrics.snapshot(), m0)["counters"]
            finally:
                # Restore the operator's own pin (if any) — this step must
                # not change how the rest of the process routes.
                if prior_impl is None:
                    del os.environ["NEMO_ANALYSIS_IMPL"]
                else:
                    os.environ["NEMO_ANALYSIS_IMPL"] = prior_impl
            missing = [
                verb
                for verb in ("fused", "diff")
                if not mc.get(f"analysis.route.{verb}.{impl}")
            ]
            if missing:
                print(
                    f"validate: NEMO_ANALYSIS_IMPL={impl} run recorded no "
                    f"analysis.route for verb(s) {missing}: "
                    f"{ {k: v for k, v in mc.items() if k.startswith('analysis.route')} }",
                    file=sys.stderr,
                )
                return 1
            route_trees[impl] = _tree(r.report_dir)
        if route_trees["sparse"].keys() != route_trees["dense"].keys():
            print(
                "validate: sparse/dense route report file sets DIVERGE: "
                f"{sorted(route_trees['sparse'].keys() ^ route_trees['dense'].keys())[:10]}",
                file=sys.stderr,
            )
            return 1
        bad3 = sorted(
            k
            for k in route_trees["sparse"]
            if route_trees["sparse"][k] != route_trees["dense"][k]
        )
        if bad3:
            print(
                "validate: sparse-routed report DIVERGES from the dense route "
                f"in {len(bad3)} file(s), e.g. {bad3[:5]}",
                file=sys.stderr,
            )
            return 1

        n_figs = len([f for f in a if f.startswith("figures")])
        fs = jx.figure_stats or {}
        print(
            "validate: ok — oracle-identical report "
            f"({len(a)} files, {n_figs} figure files, dedup {fs.get('dedup_ratio')}x, "
            "sequential-parity + cache-warm re-report identical, "
            "sparse/dense analysis routes byte-identical with every verb's "
            "route recorded)"
        )
    # The observability smokes ride the same validate path: a traced
    # two-family run must produce a loadable Perfetto trace with the three
    # promised span categories (also standalone: make trace-smoke), and
    # the operational smoke must scrape a live sidecar's /metrics +
    # /healthz and find a trace-correlated structured log record (also
    # standalone: make obs-smoke).
    rc = trace_smoke()
    if rc:
        return rc
    rc = obs_smoke()
    if rc:
        return rc
    # Corpus-store contract (also standalone: make store-smoke): cold
    # populate, warm mmap load byte-parity, deliberate corruption rejected.
    rc = store_smoke()
    if rc:
        return rc
    # Result-cache + incremental-delta contract (also standalone: make
    # delta-smoke): warm repeat = full-report hit with zero kernel
    # dispatches; grown corpus maps only the new runs, byte-identical.
    rc = delta_smoke()
    if rc:
        return rc
    # Sparse-CSR device-kernel contract (also standalone: make
    # sparse-device-smoke; ISSUE 10): forced sparse_device byte-identical
    # to the dense oracle with every verb's route recorded, giant runs on
    # the device sparse route, giant-V watermark >=5x below dense.
    rc = sparse_device_smoke()
    if rc:
        return rc
    # Serving-tier contract (also standalone: make serve-smoke): concurrent
    # identical requests coalesce into one analysis with byte-equal
    # responses, serve.* metrics live, SIGTERM drains cleanly.
    rc = serve_smoke()
    if rc:
        return rc
    # Fleet scale-out contract (also standalone: make fleet-smoke;
    # ISSUE 14): a 2-replica fleet + router serves a cold cross-replica
    # herd with ONE analysis fleet-wide, byte-identical responses, a
    # shared-tier warm hit on the non-leader, and a clean fleet drain.
    rc = fleet_smoke()
    if rc:
        return rc
    # Fleet-observability contract (also standalone: make obs-fleet-smoke;
    # ISSUE 17): federated /metrics with per-replica labels + rollups, one
    # stitched cross-process trace through the router, an injected breaker
    # trip dumping exactly one flight bundle, and /autoscale flipping up
    # under a shed surge then back down through hysteresis.
    rc = obs_fleet_smoke()
    if rc:
        return rc
    # Fault-tolerance contract (also standalone: make chaos-smoke; ISSUE 9):
    # quarantined corrupt runs, host-lane failover + breaker under injected
    # device faults, crash-safe resume after SIGKILL — all byte-identical
    # to healthy runs.
    rc = chaos_smoke()
    if rc:
        return rc
    # Out-of-core streaming contract (also standalone: make stream-smoke;
    # ISSUE 12): a tiny-budget streamed run byte-identical to the in-memory
    # oracle (figures included), a strictly lower anonymous-RSS watermark,
    # and SIGKILL-mid-stream resume via the checkpoint path.
    rc = stream_smoke()
    if rc:
        return rc
    # Batched synthesis contract (also standalone: make synth-smoke;
    # ISSUE 13): python/sparse/sparse_device repair trees byte-identical
    # with routes recorded, ranking permutation/stream-stable, batched
    # synthesis >=5x over the per-run oracle.
    rc = synth_smoke()
    if rc:
        return rc
    # Live-watch contract (also standalone: make watch-smoke; ISSUE 15):
    # a replayed 3-generation sweep produces >=3 in-order report_update
    # events over AnalyzeDirStream, each cycle O(new runs), the final
    # live report byte-identical to the post-hoc one-shot, and a
    # truncated-then-repaired file quarantines and re-ingests alone.
    rc = watch_smoke()
    if rc:
        return rc
    # Platform-profile contract (also standalone: make profile-smoke;
    # ISSUE 19): a cold cache root calibrates ONCE (bounded) on first
    # contact, a second process boots measured with zero probe
    # dispatches, env overrides win the precedence, and report trees are
    # byte-identical across profile-on / profile-off / env-forced runs.
    rc = profile_smoke()
    if rc:
        return rc
    # Ad-hoc query-engine contract (also standalone: make query-smoke;
    # ISSUE 20): every fixed verb byte-identical as a query-layer program,
    # a novel 3-pattern query's warm repeat a zero-dispatch rcache hit,
    # and the sidecar Query RPC round-trip document-identical.
    return query_smoke()


if __name__ == "__main__":
    # Every smoke asserts exact route/dispatch counters and byte-parity
    # against hand-seeded expectations — a live platform profile would
    # re-route work mid-smoke (and a cold root would calibrate against the
    # user's cache).  Pin it off for the whole harness; the profile smoke's
    # CHILDREN opt back in per scenario, and an operator can still export
    # NEMO_PROFILE explicitly to exercise a smoke under a measured profile.
    os.environ.setdefault("NEMO_PROFILE", "off")
    if "--profile-smoke" in sys.argv:
        sys.exit(profile_smoke())
    if "--trace-smoke" in sys.argv:
        sys.exit(trace_smoke())
    if "--obs-smoke" in sys.argv:
        sys.exit(obs_smoke())
    if "--store-smoke" in sys.argv:
        sys.exit(store_smoke())
    if "--delta-smoke" in sys.argv:
        sys.exit(delta_smoke())
    if "--shard-smoke" in sys.argv:
        sys.exit(shard_smoke())
    if "--sparse-device-smoke" in sys.argv:
        sys.exit(sparse_device_smoke())
    if "--serve-smoke" in sys.argv:
        sys.exit(serve_smoke())
    if "--fleet-smoke" in sys.argv:
        sys.exit(fleet_smoke())
    if "--obs-fleet-smoke" in sys.argv:
        sys.exit(obs_fleet_smoke())
    if "--chaos-smoke" in sys.argv:
        sys.exit(chaos_smoke())
    if "--stream-smoke" in sys.argv:
        sys.exit(stream_smoke())
    if "--synth-smoke" in sys.argv:
        sys.exit(synth_smoke())
    if "--watch-smoke" in sys.argv:
        sys.exit(watch_smoke())
    if "--query-smoke" in sys.argv:
        sys.exit(query_smoke())
    sys.exit(main())
