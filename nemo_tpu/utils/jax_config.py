"""Shared JAX runtime configuration for every entry point (CLI, sidecar,
bench, tests).

Platform handling exists because of how this environment exposes the TPU:
a tunnel plugin (sitecustomize) registers the device under the platform
name "axon" and force-sets ``jax_platforms="axon,cpu"`` at interpreter
start, overriding any JAX_PLATFORMS the caller exported.  Two consequences
every entry point must survive:

  * In a tunnel outage, device discovery (``jax.devices()``) HANGS rather
    than erroring — so any device touch needs a watchdog probe in a
    subprocess, never in-process (observed in rounds 1-2; VERDICT r2
    weak #3: the CLI hung >6 min).
  * Forcing ``JAX_PLATFORMS=tpu`` FAILS under the tunnel ("No jellyfish
    device found"): the local libtpu client can't initialize; the chip is
    only reachable through the tunnel's auto-selection.  So "give me the
    TPU" means *leave the selection alone*, and only explicit CPU (or
    another concrete local platform) is ever pinned.

The reference CLI always terminates — every error path is log.Fatalf
(main.go:65-292); ensure_platform() is this rebuild's equivalent contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from nemo_tpu.obs import log as _obs_log

#: Platform names that mean "use the environment's default selection".
_DEFAULT_NAMES = ("", "auto", "tpu", "axon", "default")

#: Subset of _DEFAULT_NAMES that is an *explicit demand for the device*:
#: resolution must not silently degrade to CPU for these (ADVICE r3 #1).
_EXPLICIT_DEVICE_NAMES = ("tpu", "axon")


class PlatformUnavailableError(RuntimeError):
    """An explicitly requested device platform could not be reached."""


def pin_platform(platform: str) -> None:
    """Pin jax's platform selection, overriding the sitecustomize override.

    Must run before the first device use (not necessarily before ``import
    jax`` — the tunnel's override happens at interpreter start, so a later
    ``jax.config.update`` wins)."""
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)


def probe_default_platform(
    timeout_s: float = 120.0, retries: int = 3, log=None
) -> dict | None:
    """Ask a subprocess what jax's default platform is.

    Returns {"platform": str, "n": int} or None if every attempt failed.
    The probe runs out-of-process under a hard timeout because a tunnel
    outage makes jax.devices() hang forever, taking the probing process
    with it."""
    import time

    log = log or (lambda msg: _obs_log.get_logger("nemo.platform").warning(
        "platform.probe", detail=msg
    ))
    code = (
        "import jax, json;"
        "d = jax.devices();"
        "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
    )
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                try:
                    return json.loads(out.stdout.strip().splitlines()[-1])
                except json.JSONDecodeError:
                    log(f"device probe attempt {attempt + 1}/{retries}: unparseable stdout")
                    continue
            tail = (out.stderr or "").strip().splitlines()[-1:] or ["<no stderr>"]
            log(f"device probe attempt {attempt + 1}/{retries} rc={out.returncode}: {tail[0]}")
        except subprocess.TimeoutExpired:
            log(f"device probe attempt {attempt + 1}/{retries} timed out after {timeout_s:.0f}s")
        if attempt + 1 < retries:
            time.sleep(min(30.0, 5.0 * 2**attempt))
    return None


def ensure_platform(
    requested: str | None = None,
    probe_timeout_s: float | None = None,
    probe_retries: int | None = None,
    log=None,
) -> str:
    """Resolve and apply the jax platform for this process; never hangs.

    requested:
      "cpu" (or any concrete local platform)  -> pinned immediately, no probe
      None / "auto"                           -> probe the default selection
          under a watchdog; healthy -> leave the selection alone (the only
          way to reach the tunnel device); unreachable -> pin "cpu" and warn.
      "tpu" / "axon"                          -> same probe (pinning
          JAX_PLATFORMS=tpu fails under the tunnel, so the device is still
          reached via the default selection), but the request is an explicit
          demand: if the probe fails or resolves to a host-only platform,
          raise PlatformUnavailableError instead of degrading to CPU.

    Defaults come from env: NEMO_PLATFORM (request),
    NEMO_PROBE_TIMEOUT / NEMO_PROBE_RETRIES (watchdog knobs).
    Returns the platform this process will use.
    """
    log = log or (lambda msg: _obs_log.get_logger("nemo.platform").warning(
        "platform.probe", detail=msg
    ))
    req = (requested or os.environ.get("NEMO_PLATFORM") or "auto").lower()
    if req not in _DEFAULT_NAMES and req != "cpu":
        # A concrete non-TPU platform (cuda, rocm, ...): trust the caller.
        pin_platform(req)
        return req
    if req == "cpu":
        pin_platform("cpu")
        return "cpu"
    timeout_s = probe_timeout_s if probe_timeout_s is not None else float(
        os.environ.get("NEMO_PROBE_TIMEOUT", "120")
    )
    retries = probe_retries if probe_retries is not None else int(
        os.environ.get("NEMO_PROBE_RETRIES", "2")
    )
    # "Explicit" means the CALLER demanded the device (--platform=tpu / a
    # direct ensure_platform("tpu")).  A NEMO_PLATFORM=tpu *environment
    # default* keeps the loud CPU fallback: an env-configured deployment
    # (e.g. a long-lived sidecar) should survive a tunnel outage, while a
    # user typing the flag should get an error, not a silent downgrade.
    explicit = (requested or "").lower() in _EXPLICIT_DEVICE_NAMES
    info = probe_default_platform(timeout_s, retries, log=log)
    if info is None:
        if explicit:
            raise PlatformUnavailableError(
                f"platform {req!r} explicitly requested but the device probe "
                "failed (tunnel outage or no device); refusing to silently "
                "run on CPU — pass --platform=auto to allow the fallback"
            )
        log(
            "warning: device platform unreachable (probe timed out); "
            "falling back to CPU"
        )
        pin_platform("cpu")
        return "cpu"
    if explicit and info["platform"] == "cpu":
        raise PlatformUnavailableError(
            f"platform {req!r} explicitly requested but only CPU devices are "
            f"visible (default selection resolved to {info['platform']!r} "
            f"x{info['n']}); refusing to silently run on CPU"
        )
    return info["platform"]


# --------------------------------------------------------------------------
# Version-compat shims.  The repo targets the newest jax API surface
# (jax.shard_map, lax.pcast varying-axes marking, lax.axis_size,
# jax.distributed.is_initialized); this environment pins jax 0.4.37 where
# those names live elsewhere or don't exist yet.  Every caller goes through
# these shims so the compat policy has exactly one home.


def shard_map():
    """The shard_map entry point: ``jax.shard_map`` where it exists (jax
    >= 0.5), else ``jax.experimental.shard_map.shard_map``."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map as _sm

    return _sm


def pcast_varying(x, axis_name: str):
    """Mark ``x`` device-varying over ``axis_name`` where shard_map enforces
    varying-axes typing (``lax.pcast``, jax >= 0.6); earlier versions have
    no such check and the value passes through untouched."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return x


def axis_size(axis_name: str):
    """``lax.axis_size`` (jax >= 0.5), else the classic ``psum(1, axis)``
    idiom — XLA constant-folds the literal reduction to the axis size."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` (jax >= 0.5); on older versions
    the runtime's global state records the coordinator address once
    initialize() has run."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "coordinator_address", None) is not None


def enable_compilation_cache() -> None:
    """Persist jitted kernels across process invocations (first TPU compile
    is tens of seconds; repeat invocations then load from disk).  Opt out
    with NEMO_JAX_CACHE=off; NEMO_JAX_CACHE=<dir> overrides the location."""
    cache = os.environ.get("NEMO_JAX_CACHE", "")
    if cache.lower() in ("off", "0", "none"):
        return
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        cache or os.path.join(os.path.expanduser("~"), ".cache", "nemo_tpu", "jax"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
