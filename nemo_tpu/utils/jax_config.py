"""Shared JAX runtime configuration for the entry points (CLI, sidecar)."""

from __future__ import annotations

import os


def enable_compilation_cache() -> None:
    """Persist jitted kernels across process invocations (first TPU compile
    is tens of seconds; repeat invocations then load from disk).  Opt out
    with NEMO_JAX_CACHE=off; NEMO_JAX_CACHE=<dir> overrides the location."""
    cache = os.environ.get("NEMO_JAX_CACHE", "")
    if cache.lower() in ("off", "0", "none"):
        return
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        cache or os.path.join(os.path.expanduser("~"), ".cache", "nemo_tpu", "jax"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
