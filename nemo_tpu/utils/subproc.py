"""Shared subprocess-service plumbing: free-port probe + listen gate.

Every harness that boots a sidecar subprocess (bench serve tier,
`make serve-smoke`, the obs/trace smokes) needs the same two primitives,
and one of them encodes an environment quirk worth centralizing: this
environment's grpc WEDGES channels whose first connect races the server's
bind, so the listening socket must be observed BEFORE any channel is
created — polling Health on an eagerly-created channel spins UNAVAILABLE
forever against a perfectly healthy server.
"""

from __future__ import annotations

import socket
import time


def free_port() -> int:
    """An OS-assigned currently-free TCP port (the usual bind-to-0 probe;
    the tiny TOCTOU window to the consumer's own bind is accepted)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_listening(
    port: int,
    deadline_s: float = 120.0,
    proc=None,
    host: str = "127.0.0.1",
    poll_s: float = 0.5,
) -> None:
    """Block until (host, port) accepts a TCP connection.

    Raises RuntimeError when the deadline passes or ``proc`` (a Popen,
    optional) exits first — with the exit code, so a crashed server is
    distinguishable from a slow one."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            socket.create_connection((host, port), 2.0).close()
            return
        except OSError:
            rc = proc.poll() if proc is not None else None
            if time.monotonic() > deadline or rc is not None:
                raise RuntimeError(
                    f"server never listened on {host}:{port} "
                    f"(rc={rc}, waited {deadline_s:.0f}s)"
                ) from None
            time.sleep(poll_s)
