"""Shared subprocess-service plumbing: free-port probe + listen gate.

Every harness that boots a sidecar subprocess (bench serve/fleet tiers,
`make serve-smoke` / `make fleet-smoke`, the obs/trace smokes) needs the
same primitives, and one of them encodes an environment quirk worth
centralizing: this environment's grpc WEDGES channels whose first connect
races the server's bind, so the listening socket must be observed BEFORE
any channel is created — polling Health on an eagerly-created channel
spins UNAVAILABLE forever against a perfectly healthy server.

Multi-server boots (ISSUE 14 satellite): the classic bind-to-0 probe
closes its socket before returning, so N concurrent boots probing in a
row could be handed the SAME port (the OS is free to reuse it the moment
the probe closes).  Two fixes compose here: :func:`free_port` never
repeats a port it issued recently in this process, and
:class:`PortReservation` bind-and-HOLDS a batch of ports, releasing each
one only at the instant its server boots — shrinking the TOCTOU window
from "the whole boot" to one exec.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque

#: Ports handed out recently by THIS process (free_port and
#: PortReservation both record here) — bounded, oldest forgotten first.
_ISSUED_MAX = 256
_issued: deque[int] = deque()
_issued_set: set[int] = set()
_issued_lock = threading.Lock()


def _remember_locked(port: int) -> None:
    _issued.append(port)
    _issued_set.add(port)
    while len(_issued) > _ISSUED_MAX:
        _issued_set.discard(_issued.popleft())


def free_port() -> int:
    """An OS-assigned currently-free TCP port (the usual bind-to-0 probe),
    guaranteed distinct from any port this process was handed recently —
    the multi-sidecar boot race fix: two concurrent boots each probing
    can no longer receive the same port from this process.  The residual
    TOCTOU window against OTHER processes' binds is accepted (use
    :class:`PortReservation` to shrink it for batch boots)."""
    port = 0
    for _ in range(128):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        with _issued_lock:
            if port not in _issued_set:
                _remember_locked(port)
                return port
    # The OS kept re-issuing recently-seen ports (tiny ephemeral range);
    # hand out the last probe rather than spinning forever.
    return port


class PortReservation:
    """Bind-and-hold N distinct ports for a fleet boot.

    Every port stays BOUND (so no other bind-to-0 probe — in this process
    or any other — can be handed it) until :meth:`release` frees it
    immediately before the server that will own it executes.  Use as a
    context manager so an aborted boot never leaks the held sockets::

        with PortReservation(3) as ports:
            for i, port in enumerate(ports.ports):
                ports.release(i)
                boot_server(port)
    """

    def __init__(self, n: int) -> None:
        self._socks: list[socket.socket | None] = []
        try:
            for _ in range(n):
                s = socket.socket()
                # TIME_WAIT tolerance for the holder itself; the eventual
                # server's own bind happens after release() closes this.
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", 0))
                self._socks.append(s)
        except OSError:
            self.close()
            raise
        self.ports = [s.getsockname()[1] for s in self._socks]
        with _issued_lock:
            for p in self.ports:
                _remember_locked(p)

    def release(self, i: int) -> int:
        """Free reservation ``i``'s socket and return its port — call this
        immediately before booting the server that binds it."""
        s = self._socks[i]
        if s is not None:
            self._socks[i] = None
            s.close()
        return self.ports[i]

    def close(self) -> None:
        for i, s in enumerate(self._socks):
            if s is not None:
                self._socks[i] = None
                s.close()

    def __enter__(self) -> "PortReservation":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def wait_listening(
    port: int,
    deadline_s: float = 120.0,
    proc=None,
    host: str = "127.0.0.1",
    poll_s: float = 0.5,
) -> None:
    """Block until (host, port) accepts a TCP connection.

    Raises RuntimeError when the deadline passes or ``proc`` (a Popen,
    optional) exits first — with the exit code, so a crashed server is
    distinguishable from a slow one."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            socket.create_connection((host, port), 2.0).close()
            return
        except OSError:
            rc = proc.poll() if proc is not None else None
            if time.monotonic() > deadline or rc is not None:
                raise RuntimeError(
                    f"server never listened on {host}:{port} "
                    f"(rc={rc}, waited {deadline_s:.0f}s)"
                ) from None
            time.sleep(poll_s)
