"""One home for env-knob parsing — and THE documented loud-vs-quiet policy.

Every ``NEMO_*`` knob in this codebase falls into one of two failure
policies, chosen by what a junk value would otherwise do:

  * **loud** (``policy="raise"``): knobs that pin an ALGORITHM or a
    correctness-relevant execution dimension (``NEMO_ANALYSIS_IMPL``,
    ``NEMO_SCHED``, ``NEMO_GIANT_IMPL``, the scheduler cost seeds).  A typo
    silently resolving to the default would change which code analyzes the
    corpus in exactly the dimension the operator was pinning — crash at
    startup instead.
  * **quiet** (``policy="warn"``, the default here): observability,
    serving, cache and robustness knobs on paths that may be a LONG-LIVED
    multi-tenant sidecar (``NEMO_SERVE_*``, ``NEMO_METRICS_*``,
    ``NEMO_STORE_*``, the fault-tolerance knobs below).  Raising per
    request would turn one typo'd env into a crash loop taking every
    tenant down — strictly worse than serving correct results at the
    measured default under a warning that names the junk value
    (the ``NEMO_MAX_BATCH`` / ADVICE r5 #4 precedent, revisited by
    ISSUE 8).

Callers that still carry their own parser (pre-dating this module) are
being converged here; new knobs must use these helpers so the policy table
above stays the single statement of intent.
"""

from __future__ import annotations

import os

from nemo_tpu.obs import log as obs_log

_log = obs_log.get_logger("nemo.env")


def _reject(name: str, raw: str, why: str, default, policy: str):
    if policy == "raise":
        raise ValueError(f"{name}={raw!r} {why}")
    _log.warning("env.bad_value", name=name, value=raw, detail=why, using=default)
    return default


def env_int(
    name: str, default: int, minimum: int | None = 0, policy: str = "warn"
) -> int:
    """Integer knob.  ``minimum`` is inclusive (None = unbounded)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        n = int(raw)
    except ValueError:
        return _reject(name, raw, "is not an integer", default, policy)
    if minimum is not None and n < minimum:
        return _reject(name, raw, f"must be >= {minimum}", default, policy)
    return n


def env_float(
    name: str, default: float, minimum: float | None = 0.0, policy: str = "warn"
) -> float:
    """Float knob.  ``minimum`` is inclusive (None = unbounded)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return _reject(name, raw, "is not a number", default, policy)
    if minimum is not None and v < minimum:
        return _reject(name, raw, f"must be >= {minimum}", default, policy)
    return v


_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def env_flag(name: str, default: bool, policy: str = "warn") -> bool:
    """Boolean knob accepting the usual spellings (1/true/yes/on,
    0/false/no/off)."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    return _reject(name, raw, "is not a recognized boolean", default, policy)


def env_choice(
    name: str, default: str, choices: tuple, policy: str = "raise"
) -> str:
    """Enumerated knob.  Loud by default: enum knobs pin algorithms."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    if raw in choices:
        return raw
    return _reject(
        name, raw, f"(expected one of {', '.join(choices)})", default, policy
    )


# ---------------------------------------------------------------------------
# fault-tolerance knobs (ISSUE 9) — all quiet policy: they gate DEGRADED
# operation, and a crash loop over a typo'd robustness knob would be ironic.
# ---------------------------------------------------------------------------


def quarantine_enabled() -> bool:
    """``NEMO_QUARANTINE`` (default on): per-run ingest error isolation — a
    malformed/truncated run is quarantined (recorded in the report's
    "Degraded runs" section) instead of aborting the whole corpus.  Off
    restores the fail-fast pre-ISSUE-9 behavior (a CI gate that WANTS a
    corrupt corpus to abort)."""
    return env_flag("NEMO_QUARANTINE", True)


def dispatch_timeout_s() -> float:
    """``NEMO_DISPATCH_TIMEOUT_S`` (default 0 = disabled): hard wall-clock
    deadline on one device-lane dispatch.  Past it the scheduler ABANDONS
    the wedged dispatch thread (it cannot be cancelled mid-XLA), counts a
    breaker failure, and fails the job over to the sparse-host lane — the
    escalation past the PR-4 log-only watchdog (``NEMO_SLOW_DISPATCH_MS``)."""
    return env_float("NEMO_DISPATCH_TIMEOUT_S", 0.0)


def breaker_failures() -> int:
    """``NEMO_BREAKER_FAILURES`` (default 3): consecutive device-lane
    failures that trip the circuit breaker into host-only degraded mode."""
    return max(1, env_int("NEMO_BREAKER_FAILURES", 3, minimum=1))


def breaker_cooldown_s() -> float:
    """``NEMO_BREAKER_COOLDOWN_S`` (default 30): how long an OPEN breaker
    short-circuits the device lane before letting one half-open probe
    through."""
    return env_float("NEMO_BREAKER_COOLDOWN_S", 30.0)


def failover_backoff_s() -> float:
    """``NEMO_FAILOVER_BACKOFF_S`` (default 0.05): base of the jittered
    backoff slept before re-running a failed device job on the host lane
    (gives a transiently wedged tunnel a beat without stalling the drain)."""
    return env_float("NEMO_FAILOVER_BACKOFF_S", 0.05)
