"""JaxBackend: the batched TPU graph-analytics engine.

All per-run graph analyses run as fixed-shape array kernels over size-bucketed
run batches (nemo_tpu.ops.*): ONE fused analysis_step dispatch per joint
(pre, post) bucket computes condition marking, clean-copy + chain contraction,
and prototype bitsets for the whole batch — the axis the reference loops over
sequentially, one Bolt round-trip at a time (SURVEY.md §2.3) — plus one
good-run-anchored differential-provenance dispatch over all failed runs.
Runs above NEMO_GIANT_V nodes auto-dispatch to the node-sharded giant path
(parallel/giant.py).  Host work is limited to packing, report
materialization, and the good-run-only trigger queries shared with the
oracle backend (analysis/queries.py).
"""

from __future__ import annotations

import os
import time
import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from nemo_tpu import obs
from nemo_tpu.obs import log as _obs_log
from nemo_tpu.utils import chaos as _chaos
from nemo_tpu.analysis.corrections import synthesize_corrections, synthesize_extensions
from nemo_tpu.analysis.protos import intersect_proto, missing_from, union_proto, wrap_code
from nemo_tpu.analysis.queries import (
    extension_candidates,
    find_post_triggers,
    find_pre_triggers,
)
from nemo_tpu.graphs.packed import (
    TYPE_NAMES,
    CorpusGraphs,
    CorpusVocab,
    PackedBatch,
    bucket_size,
    bucketize_pairs,
    bucketize_pairs_corpus,
    pack_batch,
    pack_graph,
    rewrite_run_prefix,
    unpack_to_pgraph,
)
from nemo_tpu.graphs.pgraph import PGraph, build_pgraph
from nemo_tpu.ingest.datatypes import Goal, MissingEvent, Rule
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.ops.adjacency import build_adjacency
from nemo_tpu.ops.condition import mark_condition_holds
from nemo_tpu.ops.diff import diff_masks
from nemo_tpu.ops.proto import DEPTH_INF, all_rule_bits, proto_rule_bits
from nemo_tpu.ops.simplify import clean_masks, collapse_chains
from nemo_tpu.report.dot import DotGraph
from nemo_tpu.report.figures import create_diff_dot, create_dot

from .base import GraphBackend
from .python_ref import CLEAN_OFFSET, DIFF_OFFSET

_log = _obs_log.get_logger("nemo.backend")


@partial(jax.jit, static_argnames=("v", "cond_tid", "num_tables"))
def _k_condition(edge_src, edge_dst, edge_mask, is_goal, table_id, node_mask, v, cond_tid, num_tables):
    adj = build_adjacency(edge_src, edge_dst, edge_mask, v)
    return mark_condition_holds(adj, is_goal, table_id, node_mask, cond_tid, num_tables)


@partial(jax.jit, static_argnames=("v",))
def _k_simplify(edge_src, edge_dst, edge_mask, is_goal, type_id, node_mask, v):
    adj = build_adjacency(edge_src, edge_dst, edge_mask, v)
    adj_clean, alive = clean_masks(adj, is_goal, node_mask)
    return collapse_chains(adj_clean, is_goal, type_id, alive)


@partial(jax.jit, static_argnames=("num_tables", "max_depth"))
def _k_proto(adj, is_goal, alive, table_id, achieved_pre, num_tables, max_depth):
    bits, min_depth = proto_rule_bits(
        adj, is_goal, alive, table_id, achieved_pre, num_tables, max_depth
    )
    present = all_rule_bits(is_goal, alive, table_id, num_tables)
    return bits, min_depth, present


@partial(jax.jit, static_argnames=("v", "max_depth"))
def _k_diff(edge_src, edge_dst, edge_mask, is_goal, node_mask, label_id, fail_bits, v, max_depth):
    adj_good = build_adjacency(edge_src, edge_dst, edge_mask, v)[0]
    return diff_masks(adj_good, is_goal, node_mask, label_id, fail_bits, max_depth)


#: Field order of models.pipeline_model.BatchArrays, used to (de)serialize
#: the fused verb's inputs through the executor's named-array contract.
_BA_FIELDS = (
    "edge_src",
    "edge_dst",
    "edge_mask",
    "is_goal",
    "table_id",
    "label_id",
    "type_id",
    "node_mask",
)


def _k_giant(*args):
    """Giant-graph dispatch: ONE run whose node count exceeds the dense
    bucket threshold analyzes on a node-sharded mesh with closure-free
    kernels (parallel/giant.py) — the 'ring attention' analog of SURVEY.md
    §5 reaching production instead of living only in tests (VERDICT r2
    missing #4).  The two label planes carry giant_plan's exact union-find
    component labels, used when the chains are not verified-linear."""
    from nemo_tpu.models.pipeline_model import BatchArrays
    from nemo_tpu.parallel.giant import giant_analysis_step

    pre = BatchArrays(*args[:8])
    post = BatchArrays(*args[8:16])
    pre_labels, post_labels = args[16:18]
    (v, pre_tid, post_tid, num_tables, max_depth, comp_linear, proto_depth,
     pack_out) = args[18:]
    return giant_analysis_step(
        pre,
        post,
        v=v,
        pre_tid=pre_tid,
        post_tid=post_tid,
        num_tables=num_tables,
        max_depth=max_depth,
        comp_linear=bool(comp_linear),
        proto_depth=proto_depth,
        pre_labels=pre_labels,
        post_labels=post_labels,
        pack_out=bool(pack_out),
    )


def _k_fused(*args):
    """The production pipeline's device program: ONE dispatch per bucket
    computing condition marking, simplification, and prototypes for both
    conditions of a run batch — the same fused analysis_step the benchmark
    times and the sidecar's Analyze RPC serves, so the shipped CLI path and
    the benched path are one code path (VERDICT r2 weak #1)."""
    from nemo_tpu.models.pipeline_model import BatchArrays, analysis_step

    pre = BatchArrays(*args[:8])
    post = BatchArrays(*args[8:16])
    (v, pre_tid, post_tid, num_tables, num_labels, max_depth, with_diff,
     comp_linear, pack_out) = args[16:]
    return analysis_step(
        pre,
        post,
        v=v,
        pre_tid=pre_tid,
        post_tid=post_tid,
        num_tables=num_tables,
        num_labels=num_labels,
        max_depth=max_depth,
        with_diff=bool(with_diff),
        comp_linear=bool(comp_linear),
        pack_out=bool(pack_out),
    )


def _k_sparse_fused(*args):
    """Sparse-device fused step (ISSUE 10 tentpole): the same per-bucket
    analysis as _k_fused computed as gather/scatter frontier waves over the
    packed [B,E] edge planes (ops/sparse_device.py) — O(B*(V+E)) device
    memory instead of the dense [B,V,V] adjacency wall, with the clean
    adjacency returned as a contracted edge list the backend densifies
    per figure-selected row (CsrAdjRows)."""
    from nemo_tpu.models.pipeline_model import BatchArrays
    from nemo_tpu.ops.sparse_device import sparse_device_step

    pre = BatchArrays(*args[:8])
    post = BatchArrays(*args[8:16])
    (v, pre_tid, post_tid, num_tables, comp_linear, pack_out) = args[16:]
    return sparse_device_step(
        pre,
        post,
        v=v,
        pre_tid=pre_tid,
        post_tid=post_tid,
        num_tables=num_tables,
        comp_linear=bool(comp_linear),
        pack_out=bool(pack_out),
    )


def _k_sparse_diff(edge_src, edge_dst, edge_mask, is_goal, node_mask, label_id, fail_bits, v):
    """Sparse-device differential provenance: the diff verb's frontier
    waves over the good run's edge list (ops/sparse_device.py), edge_keep
    returned as a mask over the edge list (the diff_masks_host convention)
    instead of dense [B,V,V] planes."""
    from nemo_tpu.ops.sparse_device import diff_masks_sparse_device

    return diff_masks_sparse_device(
        edge_src, edge_dst, edge_mask, is_goal, node_mask, label_id, fail_bits, v
    )


def _k_synth_ext(
    edge_src, edge_dst, edge_mask, is_goal, node_mask, type_id, table_id, holds,
    v, num_tables,
):
    """Batched correction/extension synthesis kernel (ISSUE 13): per-run
    extension-candidate table bitsets over the packed antecedent [B,E]
    edge planes (ops/sparse_device.py:synth_ext_candidates) — the
    reference's baseline-run-only PGraph walk generalized to every run of
    a bucket in one dispatch.  Row-independent, so the serving tier's
    continuous batcher may merge compatible dispatches."""
    from nemo_tpu.ops.sparse_device import synth_ext_candidates

    return synth_ext_candidates(
        edge_src, edge_dst, edge_mask, is_goal, node_mask, type_id, table_id,
        holds, v=v, num_tables=num_tables,
    )


def _device_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` bracketing one kernel dispatch, so
    a jax.profiler device capture running alongside (CLI --profile, sidecar
    --profiler-port) carries the same labels as the obs host spans and the
    two traces line up in one Perfetto view.  No-op where the API is absent
    (older jax) — host-side obs spans don't depend on it."""
    ann = getattr(jax.profiler, "TraceAnnotation", None)
    if ann is None:
        import contextlib

        return contextlib.nullcontext()
    return ann(name)


#: Per-signature kernel cost table (ISSUE 4 tentpole): one record per
#: (verb, input shapes/dtypes, statics) ever dispatched by this process,
#: with the XLA cost model's FLOPs / bytes-accessed estimates captured at
#: first sight and the first dispatch's wall (trace + compile + first
#: execute — the cost a new signature makes a user pay).  Consumed by
#: telemetry.json (kernel_cost_snapshot), the bench e2e rows, and the
#: roofline-style gauges in the metrics registry.
_KERNEL_COSTS: dict[tuple, dict] = {}
#: AOT-jitted wrappers for the dict-returning verbs (whose dispatch `fn` is
#: a plain function around an inner jit) so lower().cost_analysis() has a
#: jittable callable; never executed, only lowered.
_COST_JITS: dict[str, object] = {}


def _cost_analysis_enabled() -> bool:
    """NEMO_COST_ANALYSIS=0 disables the per-signature cost capture (it
    costs one extra trace+lower per compiled signature — negligible next
    to the compile it rides on, but an operator diagnosing trace-time
    itself needs the off switch)."""
    return os.environ.get("NEMO_COST_ANALYSIS", "").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _kernel_cost_analysis(verb: str, fn, args, statics) -> dict:
    """Best-effort XLA cost estimates for one dispatch signature:
    {"flops": float|None, "bytes_accessed": float|None}.  Uses the AOT
    ``lower(...).cost_analysis()`` path — an HLO-level analysis, no second
    backend compile — wrapping the plain dict-returning verbs in a jit of
    their own (never executed).  Any failure returns Nones: cost numbers
    are observability, they must never fail a dispatch."""
    out = {"flops": None, "bytes_accessed": None}
    try:
        target = fn
        if verb in ("fused", "giant", "sparse_fused", "sparse_diff", "synth_ext"):
            target = _COST_JITS.get(verb)
            if target is None:
                n_arr = len(LocalExecutor.VERBS[verb][1])
                n_stat = len(LocalExecutor.VERBS[verb][2])
                target = _COST_JITS[verb] = jax.jit(
                    fn, static_argnums=tuple(range(n_arr, n_arr + n_stat))
                )
        ca = target.lower(*args, *statics).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            out["flops"] = float(ca.get("flops", 0.0)) or None
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0)) or None
    except Exception:  # lint: allow-silent-except — cost numbers are observability; Nones are the documented fallback
        pass
    return out


def _cost_signature(verb: str, arrays: dict, params: dict) -> tuple:
    """The dispatch-signature key of the cost table: verb + per-input
    (name, shape, dtype) + sorted statics — exactly what determines the
    compiled program (modulo the traced-scalar table ids, deliberately)."""
    shapes = tuple(
        (n, tuple(np.shape(a)), str(getattr(a, "dtype", type(a).__name__)))
        for n, a in sorted(arrays.items())
        if a is not None
    )
    return (verb, shapes, tuple(sorted((k, int(v)) for k, v in params.items())))


def _record_kernel_cost(
    verb: str,
    sig: tuple,
    fn,
    args,
    statics,
    wall_s: float,
    compiled: bool,
    rows_frac: float = 1.0,
    pad_rows: int = 0,
) -> None:
    """First sight of a signature: capture cost estimates + the dispatch
    wall (the compile wall, when the jit cache says this dispatch
    compiled); later sights: bump the dispatch count and flow the
    signature's per-execution estimates into the cumulative counters.

    ``rows_frac`` is real rows / dispatched rows for the run-axis-batched
    verbs: the XLA estimates price the PADDED program (padding is what the
    compiler sees), but the cumulative flops/bytes counters — and the cost
    model the scheduler routes by — must count only real work, or the
    shard-multiple padding would inflate the very estimates that decide
    routing (ISSUE 7 satellite).  ``pad_rows`` is recorded on the signature
    so telemetry shows how much of each program is padding."""
    rec = _KERNEL_COSTS.get(sig)
    if rec is None:
        # Same bounded-growth contract as the metrics registry's series
        # cap: a long-lived sidecar fed adversarial bucket shapes must not
        # grow the cost table without bound.  512 signatures is ~50x any
        # real corpus sweep; drops are counted where operators look.  Junk
        # env warns-and-defaults like every other observability knob —
        # cost numbers must never fail a dispatch.
        try:
            cap = int(os.environ.get("NEMO_COST_MAX_SIGNATURES", "512"))
        except ValueError:
            cap = 512
        if len(_KERNEL_COSTS) >= cap:
            # Counts DISPATCHES not represented in the cost table (every
            # execution of an over-cap signature), so the cumulative
            # flops/bytes counters' blind spot is quantified in the same
            # unit they aggregate.
            obs.metrics.inc("kernel.cost.uncosted_dispatches")
            return
        cost = (
            _kernel_cost_analysis(verb, fn, args, statics)
            if _cost_analysis_enabled()
            else {"flops": None, "bytes_accessed": None}
        )
        rec = _KERNEL_COSTS[sig] = {
            "verb": verb,
            "shapes": " ".join(
                f"{n}[{','.join(map(str, s))}]{d}" for n, s, d in sig[1]
            ),
            "statics": dict(sig[2]),
            "flops": cost["flops"],
            "bytes_accessed": cost["bytes_accessed"],
            # Wall of the signature's first dispatch: trace + compile (or
            # persistent-cache load) + first execute.  `compiled` False
            # here means the in-memory jit cache already held the program
            # (another signature maps to the same traced program).
            "first_dispatch_s": wall_s,
            "compiled": bool(compiled),
            "dispatches": 0,
            "pad_rows": int(pad_rows),
        }
        if compiled:
            obs.metrics.observe("kernel.compile_s", wall_s)
            obs.metrics.gauge(f"kernel.compile_s.{verb}", wall_s)
        if rec["flops"] is not None:
            obs.metrics.gauge(f"kernel.cost.flops.{verb}", rec["flops"])
        if rec["bytes_accessed"] is not None:
            obs.metrics.gauge(f"kernel.cost.bytes.{verb}", rec["bytes_accessed"])
    rec["dispatches"] += 1
    rec["pad_rows"] = int(pad_rows)
    # Cumulative estimated work actually dispatched (per-execution cost x
    # executions), padding rows excluded via rows_frac — the numerator of
    # any throughput/roofline readout must count real work only.
    if rec["flops"] is not None:
        obs.metrics.inc("kernel.cost.flops", rec["flops"] * rows_frac)
    if rec["bytes_accessed"] is not None:
        obs.metrics.inc("kernel.cost.bytes_accessed", rec["bytes_accessed"] * rows_frac)


#: Outputs reduced over the run axis (no rows to un-pad after a sharded
#: dispatch) — mirror of parallel/mesh.py:run_step_sharded's corpus_level.
_CORPUS_LEVEL_OUTPUTS = frozenset({"proto_inter", "proto_union"})

#: (verb, v, e) -> (latest cost-table record of that shape class, the
#: dispatched batch width of that record's signature): the scheduler's
#: device-lane hint reads this to price a bucket the session has costed
#: (FLOPs from the XLA estimate) but not yet measured.  The rows ride
#: along because the class key deliberately ignores the batch dim (the
#: jit-sharing axis) while the FLOPs estimate scales with it — a hint
#: priced off a wider signature must normalize per row or it overprices
#: every narrower bucket of the same class by the width ratio.
_COST_BY_CLASS: dict[tuple[str, int, int], tuple[dict, int]] = {}


def _index_cost_class(verb: str, arrays: dict, params: dict) -> None:
    """File the signature's cost record under its (verb, V, E) shape class
    so the scheduler can look a bucket's cost up without reconstructing
    dispatch signatures.  Best effort, like all cost accounting."""
    try:
        sig = _cost_signature(verb, arrays, params)
        rec = _KERNEL_COSTS.get(sig)
        if rec is None or "v" not in params:
            return
        if verb in ("fused", "giant", "sparse_fused"):
            e = int(np.shape(arrays["pre_edge_src"])[1])
        elif verb == "synth_ext":
            e = int(np.shape(arrays["edge_src"])[1])
        else:
            e = 0
        if arrays.get("pre_is_goal") is not None:
            rows = int(np.shape(arrays["pre_is_goal"])[0])
        elif verb == "synth_ext":
            rows = int(np.shape(arrays["is_goal"])[0])
        else:
            rows = 1
        _COST_BY_CLASS[(verb, int(params["v"]), e)] = (rec, max(rows, 1))
    except Exception:  # lint: allow-silent-except — cost indexing is best-effort observability (docstring)
        pass


def _profile_value(name: str, seeded: float) -> float:
    """Measured platform-profile value for one routing constant, or the
    seeded default — the middle rung of the env > profile > seeded
    precedence (ISSUE 19; nemo_tpu/platform/profile.py).  Every budget
    helper below checks its env var FIRST with its own legacy parser, so
    NEMO_PROFILE=off (or a broken profile store) reproduces today's
    resolution bit for bit."""
    try:
        from nemo_tpu.platform import profile as _pp

        v = _pp.profile_value(name)
    except Exception:  # lint: allow-silent-except — a broken profile store must degrade to seeded constants, not sink routing (docstring)
        return seeded
    return seeded if v is None else float(v)


def sched_device_hint(job) -> float | None:
    """Device-lane cost hint for the heterogeneous scheduler
    (parallel/sched.py): the PR-4 cost table's FLOPs estimate for the job's
    shape class, normalized PER ROW of the costed signature and scaled to
    the job's DISPATCHED batch width — the class key shares one compiled
    program across batch widths, but FLOPs scale with the width, and the
    dispatch pays for the PADDED program: an un-normalized hint from a
    wide signature would overprice every narrower bucket off the device
    lane, while scaling by the real-run count would underprice a padded
    dispatch by the pad ratio.  Priced at NEMO_SCHED_FLOPS_PER_S (default
    5e9 — a host-CPU XLA ballpark; on a real accelerator the measured-wall
    EWMA takes over after one bucket anyway).  None when the class was
    never costed."""
    entry = _COST_BY_CLASS.get((job.verb, job.v, job.e))
    if entry is None:
        return None
    rec, rec_rows = entry
    if rec.get("flops") is None:
        return None
    env = os.environ.get("NEMO_SCHED_FLOPS_PER_S")
    if env is not None:
        try:
            rate = float(env)
        except ValueError:
            rate = 5e9
    else:
        rate = _profile_value("sched_flops_per_s", 5e9)
    per_row = float(rec["flops"]) / rec_rows
    rows = int(getattr(job, "rows_dispatch", 0)) or int(getattr(job, "rows", 1))
    return per_row * max(rows, 1) / max(rate, 1.0)


def kernel_cost_snapshot() -> list[dict]:
    """The per-signature cost table as JSON-able records, most-dispatched
    first — telemetry.json's `kernel_cost` section and the bench's
    `kernel_cost` row read this."""
    return sorted(
        (dict(rec) for rec in _KERNEL_COSTS.values()),
        key=lambda r: (-r["dispatches"], r["verb"], r["shapes"]),
    )


def sample_memory_watermarks() -> dict:
    """Device + host memory watermarks, sampled after dispatches and at
    report time: per-device PJRT memory_stats peaks where the backend
    exposes them (TPU), and the process peak RSS always (the CPU-fallback
    watermark — on a CPU backend the device buffers ARE host memory).
    Records the same numbers as gauges (mem.*) so they scrape."""
    import resource

    out: dict = {}
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KiB on Linux
    out["host_peak_rss_bytes"] = int(ru) * 1024
    obs.metrics.gauge("mem.host_peak_rss_bytes", out["host_peak_rss_bytes"])
    try:
        peak = in_use = 0
        seen = False
        for d in jax.devices():
            stats = d.memory_stats()
            if not stats:
                continue
            seen = True
            peak += int(stats.get("peak_bytes_in_use", 0))
            in_use += int(stats.get("bytes_in_use", 0))
        if seen:
            out["device_peak_bytes"] = peak
            out["device_bytes_in_use"] = in_use
            obs.metrics.gauge("mem.device_peak_bytes", peak)
            obs.metrics.gauge("mem.device_bytes_in_use", in_use)
    except Exception:  # lint: allow-silent-except — watermarks are observability; never fail the caller
        pass
    return out


def _jit_cache_size(verb: str, fn) -> int:
    """In-memory jit-cache entry count for a verb's underlying compiled
    function, or -1 when unknowable (the giant verb jits inside a closure).
    A dispatch that grows this count paid a trace+compile (or a persistent-
    cache disk load); one that doesn't was an in-memory cache hit — the
    compile-vs-execute boundary the obs metrics record."""
    if verb == "fused":
        from nemo_tpu.models.pipeline_model import _analysis_step_jit as fn
    elif verb == "sparse_fused":
        from nemo_tpu.ops.sparse_device import _sparse_step_jit as fn
    elif verb == "sparse_diff":
        from nemo_tpu.ops.sparse_device import _sparse_diff_jit as fn
    elif verb == "synth_ext":
        from nemo_tpu.ops.sparse_device import _synth_ext_jit as fn
    elif verb == "giant":
        return -1
    cs = getattr(fn, "_cache_size", None)
    try:
        return cs() if cs is not None else -1
    except Exception:
        return -1


#: Dispatch counter driving the throttled memory-watermark sampling.
_MEM_SAMPLE_TICK = [0]


class LocalExecutor:
    """The backend's device boundary: named kernels over named numpy arrays
    and static int params ("fused" and "diff" carry the production pipeline;
    "giant" the oversized-run path; "condition"/"simplify"/"proto" remain as
    the stable single-verb kernel API).  run() is the whole contract — the
    remote executor (service/client.py:RemoteExecutor) sends the same
    (verb, arrays, params) triple over the sidecar's Kernel RPC, and the
    sidecar dispatches right back into this class, so local and two-process
    deployments execute identical device code.
    """

    VERBS = {
        "condition": (
            _k_condition,
            ("edge_src", "edge_dst", "edge_mask", "is_goal", "table_id", "node_mask"),
            ("v", "cond_tid", "num_tables"),
            ("holds",),
        ),
        "simplify": (
            _k_simplify,
            ("edge_src", "edge_dst", "edge_mask", "is_goal", "type_id", "node_mask"),
            ("v",),
            ("adj", "alive", "type_id"),
        ),
        "proto": (
            _k_proto,
            ("adj", "is_goal", "alive", "table_id", "achieved_pre"),
            ("num_tables", "max_depth"),
            ("bits", "min_depth", "present"),
        ),
        "diff": (
            _k_diff,
            ("edge_src", "edge_dst", "edge_mask", "is_goal", "node_mask", "label_id", "fail_bits"),
            ("v", "max_depth"),
            ("node_keep", "edge_keep", "frontier_rule", "missing_goal"),
        ),
        "fused": (
            _k_fused,
            tuple(f"pre_{f}" for f in _BA_FIELDS) + tuple(f"post_{f}" for f in _BA_FIELDS),
            (
                "v",
                "pre_tid",
                "post_tid",
                "num_tables",
                "num_labels",
                "max_depth",
                "with_diff",
                "comp_linear",
                "pack_out",
            ),
            None,  # dict-returning: output names come from analysis_step
        ),
        "giant": (
            _k_giant,
            tuple(f"pre_{f}" for f in _BA_FIELDS)
            + tuple(f"post_{f}" for f in _BA_FIELDS)
            + ("pre_comp_labels", "post_comp_labels"),
            ("v", "pre_tid", "post_tid", "num_tables", "max_depth", "comp_linear",
             "proto_depth", "pack_out"),
            None,  # dict-returning, fused-compatible keys (B=1)
        ),
        "sparse_fused": (
            _k_sparse_fused,
            tuple(f"pre_{f}" for f in _BA_FIELDS) + tuple(f"post_{f}" for f in _BA_FIELDS),
            ("v", "pre_tid", "post_tid", "num_tables", "comp_linear", "pack_out"),
            None,  # dict-returning: summary keys + {cond}_clean_src/dst/mask
        ),
        "sparse_diff": (
            _k_sparse_diff,
            ("edge_src", "edge_dst", "edge_mask", "is_goal", "node_mask", "label_id", "fail_bits"),
            ("v",),
            ("node_keep", "edge_keep", "frontier_rule", "missing_goal"),
        ),
        "synth_ext": (
            _k_synth_ext,
            ("edge_src", "edge_dst", "edge_mask", "is_goal", "node_mask",
             "type_id", "table_id", "holds"),
            ("v", "num_tables"),
            ("ext_bits",),
        ),
    }

    #: The run-axis-batched dict-returning verbs: batch-width metrics, the
    #: pack_out default, and the run-mesh sharding all key off this set.
    BATCHED_VERBS = frozenset({"fused", "giant", "sparse_fused"})

    #: Fused outputs that stay on DEVICE in-process: the [B,V,V] clean
    #: adjacencies (plus alive/type rows) are only ever consumed per-row by
    #: figure materialization (_build_clean), so shipping them host-side
    #: eagerly wastes seconds of transfer at 10k-run scale — over the TPU
    #: tunnel this dominated the warm e2e wall.  The diff verb's edge_keep
    #: deliberately does NOT join this set: its consumers touch many
    #: per-run rows, and the lazy-slice dispatches cost more in compiles
    #: and RTTs than the one eager transfer (measured: cold diff 6s -> 39s
    #: when device-resident).  The remote executor still materializes
    #: everything (the wire has no device handles).
    ON_DEVICE = frozenset(
        {"pre_adj_clean", "post_adj_clean", "pre_alive", "post_alive", "pre_type", "post_type"}
    )

    #: Statics that may be absent from older clients' Kernel RPCs; 0 selects
    #: the generic (assumption-free) code path.  pack_out is special: when
    #: the caller omits it, run() resolves it from the LOCAL backend (the
    #: process that owns the device decides whether its device->host copies
    #: ride a serialized tunnel), so remote clients never need to know.
    OPTIONAL_PARAMS = frozenset({"comp_linear", "pack_out"})

    #: Array inputs that may be absent likewise; None reaches the kernel,
    #: which falls back to its assumption-free path (the giant verb without
    #: host labels runs the exact — if expensive — closure labeling).
    OPTIONAL_ARRAYS = frozenset({"pre_comp_labels", "post_comp_labels"})

    def run(
        self, verb: str, arrays: dict, params: dict, rows: int | None = None
    ) -> dict[str, np.ndarray]:
        """Returns a dict of array-likes: numpy for summary outputs, jax
        device arrays for the ON_DEVICE bulk outputs (consumers slice rows
        and np.asarray what they touch).

        ``rows`` is the caller's real-run count for the batched verbs (the
        batch arrays carry power-of-two padding rows); when given, the
        batch-width metrics and the cost accounting count only real rows
        (ISSUE 7 satellite) — absent (older remote clients), the dispatched
        width stands in, exactly the pre-sharding behavior."""
        if verb not in self.VERBS:
            raise ValueError(f"unknown kernel verb {verb!r}")
        # Chaos injection point (utils/chaos.py): with NEMO_CHAOS unset
        # this is one env lookup; armed, it can fail or wedge the first N
        # device dispatches — the scheduler's failover/breaker/deadline
        # machinery is exercised against exactly this boundary.
        _chaos.on_device_dispatch(verb)
        fn, array_names, param_names, out_names = self.VERBS[verb]
        if verb in self.BATCHED_VERBS and "pack_out" not in params:
            params = dict(params, pack_out=_pack_out_default())
        # Host->device transfer volume of this dispatch, as the bytes the
        # inputs occupy on entry (post-narrowing: _narrow_fused_arrays has
        # already run by here; pre-shard-padding — padding rows are not
        # upload the caller asked for) — the single home for the "upload
        # bytes" number bench.py used to re-derive arithmetically.  .nbytes
        # via getattr, NEVER np.asarray: an input that is already a device
        # array must not be pulled host-side just to be counted.
        upload = 0
        for a in arrays.values():
            if a is not None:
                nb = getattr(a, "nbytes", None)
                upload += int(nb) if nb is not None else np.asarray(a).nbytes
        # Batch width only for the batched verbs: the per-graph verbs'
        # is_goal is a [V] node vector, whose length is a node count, not
        # a batch size — observing it would corrupt the histogram.
        span_attrs = {"upload_bytes": upload}
        b_in = rows_real = None
        if verb in self.BATCHED_VERBS and arrays.get("pre_is_goal") is not None:
            b_in = int(np.shape(arrays["pre_is_goal"])[0])
            rows_real = min(int(rows), b_in) if rows is not None else b_in
            obs.metrics.observe("kernel.batch_rows", rows_real)
            span_attrs["rows"] = rows_real
        elif rows is not None and verb in ("condition", "simplify", "proto", "synth_ext"):
            # Serve-tier merged dispatches (nemo_tpu/serve/batch.py) pad
            # the run axis to a stable bucket and attest the REAL merged
            # row count here, so the cost accounting scales by rows_frac
            # exactly like the shard-padded fused dispatches (ISSUE 7
            # satellite 2).  No kernel.batch_rows observation: that
            # histogram is the fused/giant batch-width signal, and these
            # verbs also dispatch per-graph where is_goal is a node
            # vector — the explicit rows hint is the only trustworthy
            # batch attestation, and it feeds the cost table, not the
            # width histogram.
            b_in = int(np.shape(arrays["is_goal"])[0]) if arrays.get("is_goal") is not None else None
            if b_in is not None:
                rows_real = min(int(rows), b_in)
                span_attrs["rows"] = rows_real
        obs.metrics.inc(f"kernel.dispatches.{verb}")
        obs.metrics.inc("kernel.upload_bytes", upload)
        # Run-axis mesh sharding (ISSUE 7 tentpole): under NEMO_SHARD the
        # fused verb's batch arrays pad to the mesh multiple and place with
        # NamedSharding(mesh, P(run)) so the SAME jitted program runs SPMD
        # across the device mesh — per-run verbs and reductions stay
        # shard-local (GSPMD inserts only the row-0 broadcast and the
        # prototype all-reduces), and the host pays ONE gather per bucket
        # when the outputs materialize below.
        b_pad = b_in
        shard_n = 0
        if verb in ("fused", "sparse_fused") and b_in is not None:
            from nemo_tpu.parallel.mesh import pad_place_named_arrays, shard_plan

            place, n_dev = shard_plan()
            if place:
                # GSPMD cannot partition through a Mosaic pallas_call;
                # honor the operator's kernel pin over the mesh.  Each
                # verb checks only ITS kernel knob: the dense fused step
                # closes over NEMO_CLOSURE_IMPL, the sparse-device step
                # over NEMO_SPARSE_WAVE_IMPL (ops/sparse_device.py).
                if verb == "fused":
                    from nemo_tpu.ops.adjacency import resolve_closure_impl

                    pallas_pin = resolve_closure_impl() == "pallas"
                    pin_knob = "NEMO_CLOSURE_IMPL"
                else:
                    from nemo_tpu.ops.sparse_device import resolve_wave_impl

                    pallas_pin = resolve_wave_impl() == "pallas"
                    pin_knob = "NEMO_SPARSE_WAVE_IMPL"
                if pallas_pin:
                    warnings.warn(
                        f"NEMO_SHARD requested but {pin_knob}=pallas "
                        "cannot shard; dispatching single-device",
                        stacklevel=2,
                    )
                else:
                    arrays, b_pad = pad_place_named_arrays(arrays, b_in, n_dev)
                    shard_n = n_dev
                    span_attrs["shard_devices"] = n_dev
                    obs.metrics.inc("kernel.sharded_dispatches")
                    obs.metrics.gauge("analysis.shard.devices", n_dev)
        args = [
            (jnp.asarray(arrays[n]) if arrays.get(n) is not None else None)
            if n in self.OPTIONAL_ARRAYS
            else jnp.asarray(arrays[n])
            for n in array_names
        ]
        # OPTIONAL statics default to their safe value (0 = generic path)
        # so a sidecar can serve one protocol version ahead of its clients.
        statics = [
            int(params.get(n, 0)) if n in self.OPTIONAL_PARAMS else int(params[n])
            for n in param_names
        ]
        # The span brackets trace+compile+dispatch (device execution is
        # async; jax.profiler owns the device timeline).  The jit-cache
        # delta labels the compile-vs-execute boundary: a grown cache means
        # this dispatch paid trace/compile, an unchanged one was served
        # from the in-memory program cache.
        cs_before = _jit_cache_size(verb, fn)
        compiled = False
        t_disp = time.perf_counter()
        with obs.span(f"kernel:{verb}", **span_attrs) as sp:
            with _device_annotation(f"nemo:{verb}"):
                out = fn(*args, *statics)
            if cs_before >= 0:
                compiled = _jit_cache_size(verb, fn) > cs_before
                obs.metrics.inc(
                    "kernel.compiles" if compiled else "kernel.cache_hits"
                )
                if sp is not None:
                    sp.set(compiled=compiled)
        wall_s = time.perf_counter() - t_disp
        # Whether THIS dispatch paid a trace+compile, exposed for the
        # scheduler's feedback loop: a compile wall folded into the warm
        # cost EWMA would misroute every later same-class bucket.  Safe as
        # an instance attribute — the scheduler's device lane is one
        # thread, and it reads the flag before its next dispatch.
        self.last_dispatch_compiled = compiled
        # Cost accounting (ISSUE 4): per-signature FLOPs/bytes estimates +
        # compile wall into the cost table and the metrics registry, device
        # memory watermarks sampled while the dispatch's buffers are the
        # high-water mark, and the slow-dispatch watchdog — a structured
        # warning (route, bucket shape, upload bytes) for any dispatch past
        # NEMO_SLOW_DISPATCH_MS, so a wedged tunnel or a pathological
        # signature is a grep away instead of an unexplained wall.
        _record_kernel_cost(
            verb, _cost_signature(verb, arrays, params), fn, args, statics,
            wall_s, compiled,
            rows_frac=(rows_real / b_pad) if (rows_real is not None and b_pad) else 1.0,
            pad_rows=(b_pad - rows_real) if (rows_real is not None and b_pad) else 0,
        )
        _index_cost_class(verb, arrays, params)
        # Watermark sampling is throttled off the hot path: compiled
        # dispatches (rare, and the likeliest new high-water mark) plus
        # every 64th dispatch — peaks are monotone within a process, so a
        # periodic sample loses nothing but sub-window timing, and the
        # per-dispatch getrusage/memory_stats stack stays off the
        # thousands-of-small-dispatches paths.  telemetry.json always
        # samples once more at report time.
        _MEM_SAMPLE_TICK[0] += 1
        if compiled or _MEM_SAMPLE_TICK[0] % 64 == 0:
            sample_memory_watermarks()
        slow_ms = _obs_log.slow_dispatch_ms()
        if slow_ms and wall_s * 1000.0 > slow_ms:
            obs.metrics.inc("watchdog.slow_kernel")
            _log.warning(
                "kernel.slow_dispatch",
                verb=verb,
                wall_ms=round(wall_s * 1000.0, 1),
                threshold_ms=slow_ms,
                compiled=compiled,
                rows=span_attrs.get("rows"),
                v=int(params["v"]) if "v" in params else None,
                upload_bytes=upload,
            )
        if isinstance(out, dict):
            # The one-gather rule: all device->host traffic for this bucket
            # happens here, once, async-overlapped — under sharding this is
            # the single cross-shard gather the mesh layout allows per
            # bucket, and its wall is the scheduler's visibility into
            # shard-collection cost.
            t_gather = time.perf_counter()
            _prefetch_to_host(o for n, o in out.items() if n not in self.ON_DEVICE)
            res = {
                n: (o if n in self.ON_DEVICE else np.asarray(o)) for n, o in out.items()
            }
            if shard_n:
                # The gather span times the TRANSFER only: under pack_out
                # (the sharded default, _pack_out_default) the per-run bool
                # summaries cross the shard gather as one bit-packed uint8
                # vector per bucket — ~8x fewer gathered bool bytes
                # (ROADMAP 3b) — and the host-side unpack below happens
                # lazily, after the timed window closes.
                obs.metrics.observe(
                    "analysis.shard.gather_s", time.perf_counter() - t_gather
                )
                obs.metrics.inc(
                    "analysis.shard.gather_bytes",
                    sum(
                        a.nbytes
                        for n, a in res.items()
                        if n not in self.ON_DEVICE and hasattr(a, "nbytes")
                    ),
                )
            if "packed_summary" in res:
                res.update(
                    _unpack_summary(
                        res.pop("packed_summary"),
                        b=int(np.shape(arrays["pre_is_goal"])[0]),
                        v=int(params["v"]),
                        t=int(params["num_tables"]),
                        with_diff=bool(params.get("with_diff", 0)),
                        giant=verb == "giant",
                    )
                )
            if shard_n:
                if b_pad != b_in:
                    # Shed the shard-multiple padding rows so callers see
                    # exactly the batch width they dispatched; corpus-level
                    # reductions have no run axis to shed.
                    res = {
                        k: v if k in _CORPUS_LEVEL_OUTPUTS else v[:b_in]
                        for k, v in res.items()
                    }
            return res
        # Tuple-returning verbs always materialize: none of their outputs
        # are in ON_DEVICE, and the diff verb's consumers specifically rely
        # on host arrays (see the ON_DEVICE comment's 6s->39s measurement).
        if not isinstance(out, tuple):
            out = (out,)
        _prefetch_to_host(out)
        return {n: np.asarray(o) for n, o in zip(out_names, out)}


def _pack_out_default() -> int:
    """Whether the fused verb should fold its bool summary outputs into one
    bit-packed device->host transfer: yes on device backends (the TPU
    tunnel serializes copies at ~an RTT each, so seven transfers collapse
    to one 8x-smaller one), no on CPU where host "transfers" are free —
    UNLESS the run mesh is placing (shard_plan): the sharded gather
    crosses device boundaries regardless of platform, so the per-run bool
    summaries default to the bit-packed form there too and unpack lazily
    on host after the timed gather (ROADMAP 3b, ISSUE 10 satellite).
    Resolved by the process that OWNS the device (the sidecar server, or
    the in-process backend) — remote clients never send it.
    NEMO_PACK_XFER=0/1 overrides."""
    env = os.environ.get("NEMO_PACK_XFER", "").strip().lower()
    if env:
        # Accept the usual boolean spellings; an unrecognized value falls
        # through to the backend default rather than raising at dispatch
        # time inside the executor/server/prewarm (ADVICE r4 #1).
        if env in ("1", "true", "yes", "on"):
            return 1
        if env in ("0", "false", "no", "off"):
            return 0
        warnings.warn(
            f"NEMO_PACK_XFER={env!r} is not a recognized boolean; "
            "using the backend default",
            stacklevel=2,
        )
    if jax.default_backend() != "cpu":
        return 1
    from nemo_tpu.parallel.mesh import shard_plan

    return int(shard_plan()[0])


def _unpack_summary(
    packed: np.ndarray,
    b: int,
    v: int,
    t: int,
    with_diff: bool = False,
    giant: bool = False,
) -> dict[str, np.ndarray]:
    """Inverse of the pack_out folding (models/pipeline_model.py:
    SUMMARY_PACK_LAYOUT + DIFF_PACK_LAYOUT, or GIANT_PACK_LAYOUT for the
    giant verb): one host np.unpackbits + views, no device work."""
    from nemo_tpu.models.pipeline_model import (
        DIFF_PACK_LAYOUT,
        GIANT_PACK_LAYOUT,
        SUMMARY_PACK_LAYOUT,
    )

    if giant:
        layout = GIANT_PACK_LAYOUT
    else:
        layout = SUMMARY_PACK_LAYOUT + (DIFF_PACK_LAYOUT if with_diff else ())
    dims = {"bv": (b, v), "b": (b,), "bt": (b, t), "t": (t,)}
    flat = np.unpackbits(np.asarray(packed)).astype(bool)
    out: dict[str, np.ndarray] = {}
    ofs = 0
    for name, key in layout:
        shape = dims[key]
        n = int(np.prod(shape))
        out[name] = flat[ofs : ofs + n].reshape(shape)
        ofs += n
    return out


def _prefetch_to_host(arrays) -> None:
    """Start device->host copies for every jax array in `arrays` before any
    blocking np.asarray: over the device tunnel each synchronous transfer
    pays a full RTT (~70-90 ms measured), so N sequential fetches cost
    N x RTT while N async copies overlap into ~1 RTT + bandwidth
    (measured 4x on the fused step's outputs, VERDICT r3 weak #2)."""
    for a in arrays:
        copy = getattr(a, "copy_to_host_async", None)
        if copy is not None:
            copy()


def _giant_threshold() -> int:
    """Node count above which a run leaves the dense batched buckets for
    the giant path (parallel/giant.py) — and above which a good run's diff
    uses the sparse host computation.  Single definition, read ONCE per
    JaxBackend corpus (init_graph_db) and cached on the instance: the two
    dispatch sites (_fused and build_figures) run at different times, so a
    mid-process env change must not make them disagree — a giant run would
    dodge the dense buckets yet still hit the dense V^3 device diff
    (ADVICE r3 #3)."""
    return int(os.environ.get("NEMO_GIANT_V", "4096"))


def _giant_impl_default() -> str:
    """Crossover routing for the giant path, mirroring the diff crossover
    one function up.  Resolution order under "auto" (ISSUE 10):

      1. an explicit NEMO_ANALYSIS_IMPL umbrella covers the giant verb too
         (sparse -> host, dense -> device, sparse_device -> sparse_device)
         so one knob forces a whole route;
      2. on a REAL device, DEVICE-SPARSE first: the sparse-CSR device step
         (ops/sparse_device.py via the sparse_fused verb) analyzes a giant
         run in O(V+E) device memory — no [V,V] adjacency, no node-sharded
         dense closures — so giant-V runs stay on the accelerator instead
         of escaping to the host;
      3. on a CPU fallback, the exact sparse HOST analysis
         (parallel/giant.py:giant_analysis_host): the dense [V,V] device
         kernels there are 5-6x SLOWER than the sequential oracle
         (BENCH_r04 giant: 87.4 s vs 14.3 s warm for the 10k-node run),
         and the numpy engine beats XLA:CPU's scatter waves too.

    Host is therefore no longer the only giant escape hatch — it is the
    CPU-platform resolution and the degraded/failover mode.
    NEMO_GIANT_IMPL={auto,host,device,sparse_device} overrides (device
    keeps the dense node-sharded path — the pre-ISSUE-10 TPU default —
    selectable; host on TPU serves a tunnel-less degraded mode)."""
    impl = _giant_impl_env()
    if impl == "auto":
        umbrella = _analysis_impl_env()
        if umbrella in ("sparse", "dense", "sparse_device"):
            return {"sparse": "host", "dense": "device"}.get(umbrella, umbrella)
        # auto AND crossover both land here: a giant's own crossover is the
        # platform inversion (dense giant on CPU loses to the oracle), so
        # the per-bucket budget knob must not drag giants onto the dense
        # device path — but a real device DOES take them, sparse-first.
        return "host" if jax.default_backend() == "cpu" else "sparse_device"
    return impl


def _max_batch_default() -> int | None:
    """Run-axis dispatch bound when the backend was constructed without an
    explicit max_batch: None (one dispatch per joint bucket) on device
    backends — fewer tunnel RTTs, and the TPU executes the big padded
    batch flat out — but 2048 on CPU, where XLA:CPU degrades ~5x on the
    giant power-of-two-padded buffers (measured, B=17000 family padded to
    [32768,64,64]: 50.6 s single-dispatch vs 10.1 s in 2048-run batches —
    cache locality, not RAM: the host had 100+ GB free).  Resolved at
    init_graph_db, after the entry point's watchdog pinned a platform.
    NEMO_MAX_BATCH overrides (0 = unbounded)."""
    override = _max_batch_env()
    if override is not _NO_OVERRIDE:
        return override
    return 2048 if jax.default_backend() == "cpu" else None


#: Sentinel distinguishing "no NEMO_MAX_BATCH set" from "=0 (unbounded)".
_NO_OVERRIDE = object()


def _max_batch_env():
    """Parse + validate NEMO_MAX_BATCH (shared by the in-process and
    service backends so the semantics can never diverge): _NO_OVERRIDE
    when unset, None for 0 (unbounded), else a positive bound.

    Junk spellings WARN and fall back to the platform default — the same
    policy as the transfer knobs (NEMO_PACK_XFER / NEMO_NARROW_XFER).
    ADVICE r5 #4 originally kept this knob loud (a typo'd bound silently
    becoming the platform default changes dispatch granularity, program
    count, and peak memory in exactly the dimension the operator pinned),
    and on a one-shot CLI run a crash at init_graph_db was the right
    tripwire.  ISSUE 8 changed the calculus: the same env now reaches a
    long-lived multi-tenant sidecar, where raising per dispatch turns one
    typo into a crash loop that takes EVERY tenant's traffic down —
    strictly worse than serving correct results at the measured platform
    default under a warning that still names the junk value."""
    env = os.environ.get("NEMO_MAX_BATCH", "").strip()
    if not env:
        return _NO_OVERRIDE
    try:
        n = int(env)
    except ValueError:
        warnings.warn(
            f"NEMO_MAX_BATCH={env!r} is not an integer (0 = unbounded); "
            "using the platform default",
            stacklevel=2,
        )
        return _NO_OVERRIDE
    if n < 0:
        warnings.warn(
            f"NEMO_MAX_BATCH={n} must be >= 0 (0 = unbounded); "
            "using the platform default",
            stacklevel=2,
        )
        return _NO_OVERRIDE
    return None if n == 0 else n


def _giant_impl_env() -> str:
    """Parse + validate NEMO_GIANT_IMPL (shared by the in-process and
    service backends so the accepted spellings can never diverge)."""
    impl = os.environ.get("NEMO_GIANT_IMPL", "auto").strip().lower()
    if impl not in ("auto", "host", "device", "sparse_device"):
        raise ValueError(
            f"NEMO_GIANT_IMPL={impl!r} (expected auto, host, device, or "
            "sparse_device)"
        )
    return impl


def _analysis_impl_env() -> str:
    """Parse + validate NEMO_ANALYSIS_IMPL (shared by the in-process and
    service backends so the accepted spellings can never diverge): the
    single knob selecting the batched analysis route — "dense" (the fused
    XLA dispatch), "sparse" (the batched CSR host engine,
    ops/sparse_host.py), or "auto" (resolved by the process that owns the
    device; see _resolve_analysis_impl / the ServiceBackend override).
    Loud on junk for the same reason NEMO_GIANT_IMPL is: a typo silently
    falling back to auto would change which algorithm analyzes the corpus
    in exactly the dimension the operator was trying to pin.

    "crossover" (ISSUE 7) is auto WITHOUT the CPU-platform pin: per-bucket
    work-budget / scheduler-cost-model routing even on a host backend —
    the knob that lets the heterogeneous scheduler's both-lanes path (and
    work stealing) be exercised and benched on a CPU-only box, where plain
    auto resolves every bucket to the sparse tier."""
    impl = os.environ.get("NEMO_ANALYSIS_IMPL", "auto").strip().lower()
    if impl not in ("auto", "dense", "sparse", "sparse_device", "crossover"):
        raise ValueError(
            f"NEMO_ANALYSIS_IMPL={impl!r} (expected auto, dense, sparse, "
            "sparse_device, or crossover)"
        )
    return impl


def _analysis_host_work_budget() -> int:
    """Per-bucket crossover for the batched analysis route under
    NEMO_ANALYSIS_IMPL=auto on a DEVICE backend: buckets whose
    B x (V + E) work is at or below this run on the sparse CSR host engine
    instead of paying a device dispatch; larger buckets keep the fused
    dense dispatch the TPU eats flat out.  (On a CPU backend the platform
    is the whole signal — every bucket routes sparse; see
    _resolve_analysis_impl.)

    The default follows the measured diff-crossover economics one budget
    up (_diff_host_work_budget): a tunnel device dispatch pays ~70 ms RTT
    plus per-signature compiles, while the sparse engine's full verb set
    costs ~1 us per work unit at the stress shapes (BENCH: 6-family 1x
    sweep, sparse tier) — so ~10^5 work units is where one dispatch's
    fixed cost still dominates.  The fused dispatch carries ~8x more
    device work per unit than the diff verb but also ~8x more host sweeps,
    so the same order of magnitude holds; NEMO_ANALYSIS_HOST_WORK
    overrides for directly-attached devices (no RTT tax: lower it), and a
    measured platform profile supplies its fitted crossover when the env
    is unset (ISSUE 19)."""
    env = os.environ.get("NEMO_ANALYSIS_HOST_WORK")
    if env is not None:
        return int(env)
    return int(_profile_value("analysis_host_work", 100000))


def _synth_host_work_budget() -> int:
    """Per-bucket crossover for the synthesis kernel family under auto on
    a DEVICE backend (analysis/synth.py:synth_host_work_budget — the
    single definition; re-exported here beside its analysis-route sibling
    so the backend's knob resolution reads one module)."""
    from nemo_tpu.analysis.synth import synth_host_work_budget

    return synth_host_work_budget()


def _sparse_device_mem_bytes() -> int:
    """Dense-route memory watermark (ISSUE 10): buckets whose dense
    footprint estimate — rows x V^2 x ~4 bytes (the bool [B,V,V] adjacency
    plus its bf16 closure copies) — exceeds this route to the sparse-CSR
    device step instead of materializing the dense planes.  The default
    (256 MB) keeps every case-study bucket dense (V <= a few hundred:
    megabytes) while giant-V buckets (V in the thousands: gigabytes) stay
    on the device sparsely instead of OOMing or escaping to the host.
    NEMO_SPARSE_DEVICE_MEM_MB overrides (0 disables the watermark); a
    measured platform profile supplies the real device's headroom when
    the env is unset (ISSUE 19)."""
    env = os.environ.get("NEMO_SPARSE_DEVICE_MEM_MB")
    if env is not None:
        return int(float(env) * 1e6)
    return int(_profile_value("sparse_device_mem_mb", 256.0) * 1e6)


def _sparse_device_density() -> float:
    """Density crossover (ISSUE 10): below nnz/V^2 = this (and past
    NEMO_SPARSE_DEVICE_MIN_V nodes), the auto device route prefers the
    sparse-CSR step — each frontier wave costs O(E) instead of the dense
    [B,V]x[B,V,V] einsum's O(V^2), so the crossover is where the MXU's
    dense throughput stops covering the wasted zero work.  The default
    1/256 is deliberately conservative: at case-study shapes (V=64,
    E-bucket 256 -> density ~0.06) the dense MXU path is the measured
    winner and keeps the route; the sparse win is the large-V, E ~ V
    regime Molly's chain-heavy graphs produce.
    NEMO_SPARSE_DEVICE_DENSITY overrides (0 disables the crossover); the
    platform profile may supply a measured value when the env is unset
    (ISSUE 19 — today's calibrator records it seeded: no giant-V probe
    fits the budget)."""
    env = os.environ.get("NEMO_SPARSE_DEVICE_DENSITY")
    if env is not None:
        return float(env)
    return float(_profile_value("sparse_device_density", 1.0 / 256.0))


def _sparse_device_min_v() -> int:
    """Node floor for the density crossover: tiny-V buckets are always
    effectively dense on the MXU regardless of nominal density (a [64,64]
    matmul is one tile), so density alone must not route them sparse.
    NEMO_SPARSE_DEVICE_MIN_V overrides."""
    return int(os.environ.get("NEMO_SPARSE_DEVICE_MIN_V", "1024"))


def _diff_host_work_budget() -> int:
    """Crossover for differential provenance (VERDICT r3 task 3): jobs with
    failed_runs x (V + E_good) at or below this run on the exact sparse host
    path (ops/diff.py:diff_masks_host) instead of paying a device dispatch.

    Measured on the TPU tunnel: the host path costs ~0.18 ms for one failed
    run, ~0.15-0.18 ms/run batched at the stress shape (V=64, E~30, ~950
    failed runs -> ~150 ms per family, ~1.6 us per work unit), while the
    device path pays ~70 ms dispatch RTT plus the dense edge_keep
    [F,V,V] readback (~4 MB/family at ~8.5 MB/s tunnel bandwidth) plus a
    per-signature fresh compile (tens of seconds) — the host path wins by
    >2x at every corpus this repo generates.  The 2M default (~3 s of host
    work) is where tunnel-deployment device costs finally amortize; on
    directly-attached TPU (no tunnel RTT/bandwidth tax) lower it via
    NEMO_DIFF_HOST_WORK; a measured platform profile anchors the same
    20x ratio to its fitted analysis crossover when the env is unset
    (ISSUE 19)."""
    env = os.environ.get("NEMO_DIFF_HOST_WORK")
    if env is not None:
        return int(env)
    return int(_profile_value("diff_host_work", 2000000))


def _narrow_xfer_env() -> int | None:
    """Explicit NEMO_NARROW_XFER override: 1/0 when set to a recognized
    boolean spelling, None when unset (junk warns and counts as unset —
    the same warn-and-default policy as NEMO_PACK_XFER)."""
    env = os.environ.get("NEMO_NARROW_XFER", "").strip().lower()
    if env:
        if env in ("1", "true", "yes", "on"):
            return 1
        if env in ("0", "false", "no", "off"):
            return 0
        warnings.warn(
            f"NEMO_NARROW_XFER={env!r} is not a recognized boolean; "
            "using the backend default",
            stacklevel=2,
        )
    return None


def _narrow_xfer_default() -> int:
    """Whether the fused dispatch narrows its upload dtypes: yes on device
    backends (the bytes cross a bandwidth-priced transfer), no on CPU
    where "transfer" is a pointer handoff and the astype copies + the
    in-program widening pass are pure cost (measured ~1 s of the 8 s CPU
    warm e2e at 1x).  Same platform logic and spelling rules as
    NEMO_PACK_XFER one function down; NEMO_NARROW_XFER=0/1 overrides
    (tests pin =1 so the narrow path stays covered on the CPU suite).

    This is the LOCAL-process resolution, correct only when this process
    owns the device; ServiceBackend overrides _resolve_narrow_xfer instead
    (ADVICE r5 #1) — its upload crosses the Kernel RPC, which is
    bandwidth-priced regardless of the client's own jax platform."""
    override = _narrow_xfer_env()
    if override is not None:
        return override
    return int(jax.default_backend() != "cpu")


def _narrow_fused_arrays(
    arrays: dict, v: int, num_tables: int, with_diff: bool, narrow: bool | None = None
) -> dict:
    """Shrink the host->device upload of the fused verb's integer planes
    (models/pipeline_model.py:widen_batch casts back inside the compiled
    program): edge indices are < v, table ids < num_tables (-1 pad), type
    ids <= 3 — int8/int16 carries them at 1/4 / 1/2 the bytes of int32.
    On the TPU tunnel the upload is bandwidth-priced, so at stress scale
    (hundreds of MB of packed planes) this is wall time off the e2e
    critical path; the same narrowing shrinks the Kernel RPC payloads
    (service codec is dtype-generic).  With the diff tail off, the label
    plane is replaced by a [1,1] stub — the trace never reads it, so only
    its bytes disappear.

    `narrow` is the backend's resolved gate (_resolve_narrow_xfer — the
    in-process and sidecar deployments resolve it differently); None
    falls back to the local-process default for standalone callers
    (prewarm mirrors the in-process deployment this way)."""
    if not (_narrow_xfer_default() if narrow is None else narrow):
        return arrays

    def _narrow_plane(a: np.ndarray, bound: int) -> np.ndarray:
        if bound <= 127:
            return a.astype(np.int8)
        if bound <= 32767:
            return a.astype(np.int16)
        return a

    out = dict(arrays)
    for prefix in ("pre", "post"):
        for name, bound in (
            ("edge_src", v),
            ("edge_dst", v),
            ("table_id", num_tables),
            ("type_id", 8),
        ):
            key = f"{prefix}_{name}"
            out[key] = _narrow_plane(np.asarray(out[key]), bound)
        if not with_diff:
            out[f"{prefix}_label_id"] = np.zeros((1, 1), dtype=np.int8)
    return out


def _verb_arrays(pre_b: PackedBatch, post_b: PackedBatch) -> dict[str, np.ndarray]:
    """The fused/giant verbs' named-array inputs for one (pre, post) bucket."""
    return {
        f"{prefix}_{f}": getattr(b, f)
        for prefix, b in (("pre", pre_b), ("post", post_b))
        for f in _BA_FIELDS
    }


def _wrap_sparse_clean(res: dict, v: int) -> dict:
    """sparse_fused executor output -> fused-compatible result dict: the
    contracted {cond}_clean_src/dst/mask edge planes become lazy
    {cond}_adj_clean views (ops/sparse_device.py:CsrAdjRows) that densify
    exactly the rows downstream consumers touch — the dense [B,V,V] plane
    the figure row-gathers index is never materialized bucket-wide."""
    from nemo_tpu.ops.sparse_device import CsrAdjRows

    out = dict(res)
    for cond in ("pre", "post"):
        out[f"{cond}_adj_clean"] = CsrAdjRows(
            out.pop(f"{cond}_clean_src"),
            out.pop(f"{cond}_clean_dst"),
            out.pop(f"{cond}_clean_mask"),
            v=v,
        )
    return out


class _LazyGraphs:
    """Mapping (run, cond) -> PGraph, materialized on first access.

    Host property-graphs exist only for report rendering and the good-run
    trigger queries; at stress scale (10k+ runs) building one per run would
    dominate wall clock (VERDICT r1), so they materialize lazily — the
    figure policy decides which runs ever touch one."""

    def __init__(self, build) -> None:
        self._build = build
        self._cache: dict[tuple[int, str], PGraph] = {}

    def __getitem__(self, key: tuple[int, str]) -> PGraph:
        g = self._cache.get(key)
        if g is None:
            g = self._cache[key] = self._build(key)
        return g

    def __setitem__(self, key: tuple[int, str], value: PGraph) -> None:
        self._cache[key] = value


class _LazyCondHolds(dict):
    """(run iteration, cond) -> per-node condition_holds row, materialized
    on first access from the fused bucket outputs (ISSUE 12): the eager
    corpus-wide fill was a 2B-iteration host loop slicing a row per run,
    while the consumers — figure-selected property-graph builds and the
    good run's diff backdrop — touch a policy-bounded handful.  Behaves as
    the dict it replaces (``get``/``[]``/``in``); a miss on a key the fused
    step never produced raises KeyError exactly like the old dict."""

    def __init__(self, fused) -> None:
        super().__init__()
        self._fused = fused
        index: dict[tuple[int, str], tuple[int, int]] = {}
        for bi, (pre_b, post_b, _res) in enumerate(fused):
            for row, rid in enumerate(pre_b.run_ids):
                index[(rid, "pre")] = (bi, row)
            for row, rid in enumerate(post_b.run_ids):
                index[(rid, "post")] = (bi, row)
        self._index = index

    def __missing__(self, key):
        bi, row = self._index[key]  # KeyError propagates like a dict miss
        pre_b, post_b, res = self._fused[bi]
        cond = key[1]
        b = pre_b if cond == "pre" else post_b
        val = self[key] = np.asarray(res[f"{cond}_holds"][row])[
            : int(b.n_nodes[row])
        ]
        return val

    def get(self, key, default=None):
        # dict.get never consults __missing__ — route through __getitem__.
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key) -> bool:
        return dict.__contains__(self, key) or key in self._index


class _LazyAchievedPre(dict):
    """iteration -> achieved_pre flag, lazily sliced from the fused bucket
    outputs (same contract as :class:`_LazyCondHolds`)."""

    def __init__(self, fused) -> None:
        super().__init__()
        self._fused = fused
        index: dict[int, tuple[int, int]] = {}
        for bi, (pre_b, _post_b, _res) in enumerate(fused):
            for row, rid in enumerate(pre_b.run_ids):
                index[rid] = (bi, row)
        self._index = index

    def __missing__(self, key):
        bi, row = self._index[key]
        res = self._fused[bi][2]
        val = self[key] = bool(np.asarray(res["achieved_pre"][row]))
        return val

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key) -> bool:
        return dict.__contains__(self, key) or key in self._index


class _CorpusPacked:
    """Lazy (run iteration, cond) -> PackedGraph mapping over a NativeCorpus
    (packed-first ingest): graphs materialize as array views on first access
    instead of 2N eager Python repacks (VERDICT r3 task 1)."""

    def __init__(self, graphs: CorpusGraphs, row_by_iter: dict[int, int]) -> None:
        self._graphs = graphs
        self._row_by_iter = row_by_iter

    def __getitem__(self, key: tuple[int, str]):
        rid, cond = key
        return self._graphs.get(cond, self._row_by_iter[rid])


class JaxBackend(GraphBackend):
    #: run_debug's auto ingest policy keys off this: the backend consumes
    #: packed corpus arrays directly, so the pipeline may skip building the
    #: per-goal Python object tree entirely (ingest/native.py:RawProv).
    supports_packed_ingest = True
    #: Per-run decomposition hooks below are implemented, so the pipeline's
    #: segment-incremental map/reduce (analysis/delta.py) can map a store
    #: segment's runs in isolation and merge cached per-segment partials.
    supports_delta = True
    #: Per-run synthesis candidates implemented as a batched kernel family
    #: (the synth_ext verb + its sparse-host twin, ISSUE 13).
    supports_synth = True

    def __init__(self, max_batch: int | None = None, executor=None) -> None:
        self.max_batch = max_batch
        #: resolved dispatch bound; finalized in init_graph_db (the platform
        #: default needs jax.default_backend(), unsafe before the watchdog).
        self._max_batch = max_batch
        # The device boundary.  LocalExecutor runs kernels in-process; the
        # ServiceBackend passes a RemoteExecutor that sends each call to the
        # gRPC sidecar instead (north-star two-process architecture).
        self.executor = executor or LocalExecutor()
        self.molly: MollyOutput | None = None
        self.vocab = CorpusVocab()
        self.packed: dict[tuple[int, str], object] = {}
        self.raw = _LazyGraphs(self._build_raw)
        self.clean = _LazyGraphs(self._build_clean)
        self.cond_holds: dict[tuple[int, str], np.ndarray] = {}
        self.achieved_pre: dict[int, bool] = {}
        # Per condition: list of (batch, adj, alive, type_id) kernel outputs.
        self.simplified: dict[str, list[tuple[PackedBatch, np.ndarray, np.ndarray, np.ndarray]]] = {}
        # (run, cond) -> (bucket index, row) into self.simplified[cond].
        self._simplified_row: dict[tuple[int, str], tuple[int, int]] = {}
        # Joint-bucket fused outputs: [(pre_batch, post_batch, out_dict)].
        self._fused_out: list[tuple[PackedBatch, PackedBatch, dict[str, np.ndarray]]] | None = None
        # Memoized _proto_tables_by_run extraction (per corpus).
        self._proto_tables_cache = None
        # Prefetch-staged fused inputs (stage_fused_inputs), adopted by the
        # next _fused on this instance; None outside the streamed pipeline.
        self._staged_inputs: dict | None = None
        # (run, cond) -> host-materialized (alive, adj, type) rows.
        self._clean_rows: dict[tuple[int, str], tuple] = {}
        self._run_by_iter: dict[int, object] = {}
        self._giant_v = _giant_threshold()
        # Resolved in init_graph_db, not here: "auto" reads
        # jax.default_backend(), which may touch the device — only safe
        # after the entry point's watchdog has pinned a platform.
        self._giant_impl = None
        self._narrow_xfer: bool | None = None
        self._diff_host_work = _diff_host_work_budget()
        # Batched-analysis route knobs; resolved in init_graph_db ("auto"
        # reads jax.default_backend(), unsafe before the watchdog).
        self._analysis_impl: str | None = None
        self._analysis_host_work = _analysis_host_work_budget()
        self._sparse_device_mem = _sparse_device_mem_bytes()
        self._sparse_device_density = _sparse_device_density()
        self._sparse_device_min_v = _sparse_device_min_v()
        # Synthesis route knobs (ISSUE 13); resolved in init_graph_db
        # ("auto" reads jax.default_backend(), unsafe before the watchdog).
        self._synth_impl: str | None = None
        self._synth_host_work = _synth_host_work_budget()
        #: impl the last _fused giant dispatch actually took (None = no
        #: giant runs in the corpus) — surfaced in the bench giant row.
        self.giant_impl_used = None
        #: per-dispatch route records (verb/route/rows/v/e/work/reason) for
        #: the last corpus — the bench JSON and route tests read these.
        self.analysis_routes: list[dict] = []
        # Packed-first ingest state (native corpus arrays; else None/empty).
        self._corpus = None
        self._corpus_graphs: CorpusGraphs | None = None
        self._row_by_iter: dict[int, int] = {}
        # iteration -> parse-time linearity flag (AND over colliding rows).
        self._lin_by_iter: dict[int, bool] = {}

    def _resolve_max_batch(self) -> int | None:
        """Platform-default run-axis dispatch bound (see _max_batch_default);
        ServiceBackend overrides — its device lives in the sidecar."""
        return _max_batch_default()

    def _resolve_giant_impl(self) -> str:
        """Giant crossover routing hook: the in-process backend resolves
        "auto" against the local device platform (_giant_impl_default);
        ServiceBackend overrides — its device lives in the sidecar, so the
        client's platform is the wrong signal."""
        return _giant_impl_default()

    def _resolve_analysis_impl(self) -> str:
        """Batched-analysis route (ISSUE 3 tentpole), resolved by the
        process that OWNS the device: an explicit NEMO_ANALYSIS_IMPL wins;
        "auto" on a CPU backend routes EVERY dense bucket to the sparse
        CSR host engine (measured: the dense XLA:CPU kernels run the wrong
        algorithm for the platform — the giant-row precedent showed the
        sparse host analysis ~34x faster than the sequential oracle where
        the dense path was 5-6x slower, and the 10x stress put 127 of
        162 s in the dense CPU kernels); "auto" on a device backend stays
        per-bucket: the measured-crossover work budget decides in
        _analysis_route.  ServiceBackend overrides — its device lives in
        the sidecar (the narrowing/giant precedents)."""
        impl = _analysis_impl_env()
        if impl == "auto" and jax.default_backend() == "cpu":
            return "sparse"
        # "crossover" passes through: _analysis_route's per-bucket budget
        # branch handles any impl that is neither sparse nor dense.
        return impl

    def _resolve_synth_impl(self) -> str:
        """Synthesis-kernel route (ISSUE 13), resolved by the process that
        OWNS the device (the NEMO_ANALYSIS_IMPL precedent): an explicit
        NEMO_SYNTH_IMPL wins ("python" keeps the per-run PGraph oracle);
        "auto" on a CPU backend routes every bucket through the bincount
        host twin (a host scatter pass always beats XLA:CPU scatter waves
        plus dispatch overhead), and on a device backend stays per-bucket:
        the NEMO_SYNTH_HOST_WORK crossover decides in _synth_route.
        ServiceBackend overrides — its device lives in the sidecar."""
        from nemo_tpu.analysis.synth import synth_impl_env

        impl = synth_impl_env()
        if impl == "auto" and jax.default_backend() == "cpu":
            return "sparse"
        return impl

    def _synth_route(self, rows: int, v: int, e: int) -> tuple[str, str, int]:
        """Per-bucket route decision for the synthesis verb: (route,
        reason, work).  Routes: "sparse" (the bincount host twin),
        "sparse_device" (the synth_ext device kernel); the "python"
        oracle route short-circuits before bucketing (synth_candidates).
        Auto: the synth kernel is a handful of single-step scatters, so
        the dispatch-cost crossover (NEMO_SYNTH_HOST_WORK) is the whole
        signal — there is no dense twin to weigh memory against."""
        work = rows * (v + e)
        impl = self._synth_impl
        if impl in ("sparse", "sparse_device"):
            from nemo_tpu.analysis.synth import synth_impl_env

            return impl, "forced" if synth_impl_env() != "auto" else "platform", work
        if work <= self._synth_host_work:
            return "sparse", "crossover", work
        return "sparse_device", "crossover", work

    def _analysis_route(
        self, rows: int, v: int, e: int, rows_dispatch: int | None = None
    ) -> tuple[str, str, int]:
        """Per-bucket route decision: (route, reason, work).  `work` is the
        sparse engine's cost model B x (V + E) — the crossover input the
        route records expose (analysis.route spans, bench JSON).
        ``rows_dispatch`` is the PADDED batch width the dense dispatch
        would materialize (run-axis bucket + shard multiple) — the memory
        watermark must price what the device allocates, not the real-run
        count, or a 1-run giant-adjacent bucket padded 8-wide slips past
        the guard onto the dense route it would OOM.

        Routes: "sparse" (the CSR host engine), "dense" (the fused [B,V,V]
        device dispatch), "sparse_device" (the CSR device step, ISSUE 10).
        Auto on a device backend decides in three steps: tiny buckets go
        host (the dispatch-cost crossover); buckets whose dense footprint
        would cross the memory watermark go sparse-device (reason "mem" —
        the giant-V wall); very sparse large-V buckets go sparse-device
        (reason "density"); everything else keeps the dense MXU dispatch."""
        work = rows * (v + e)
        impl = self._analysis_impl
        if impl in ("sparse", "dense", "sparse_device"):
            return impl, "forced" if _analysis_impl_env() != "auto" else "platform", work
        # auto on a device backend: sparse only below the measured budget
        # (a device dispatch's fixed RTT/compile cost dominates tiny
        # buckets; the big padded batches belong on the accelerator).
        if work <= self._analysis_host_work:
            return "sparse", "crossover", work
        if (
            self._sparse_device_mem
            and max(rows_dispatch or 0, rows) * v * v * 4 > self._sparse_device_mem
        ):
            return "sparse_device", "mem", work
        if (
            self._sparse_device_density
            and v >= self._sparse_device_min_v
            and e <= v * v * self._sparse_device_density
        ):
            return "sparse_device", "density", work
        return "dense", "crossover", work

    def _record_route(
        self, verb: str, route: str, rows: int, v: int, e: int, work: int, reason: str
    ) -> dict:
        """One analysis.route record: a metrics counter per (verb, route),
        an entry in self.analysis_routes, and the attr dict the caller
        wraps the routed execution's span with."""
        obs.metrics.inc(f"analysis.route.{verb}.{route}")
        rec = {
            "verb": verb,
            "route": route,
            "rows": int(rows),
            "v": int(v),
            "e": int(e),
            "work": int(work),
            "reason": reason,
        }
        self.analysis_routes.append(rec)
        return rec

    def _resolve_narrow_xfer(self) -> bool:
        """Upload-dtype narrowing gate: in-process, the local platform
        decides (narrow when the bytes cross a real device transfer);
        ServiceBackend overrides — its upload crosses the Kernel RPC, so
        the client's own platform is the wrong signal (ADVICE r5 #1)."""
        return bool(_narrow_xfer_default())

    # ------------------------------------------------------------------ setup

    def init_graph_db(self, conn: str, molly: MollyOutput) -> None:
        # Platform self-calibration trigger (ISSUE 19): first contact on a
        # cold cache root runs ONE bounded microprobe suite and persists
        # the fingerprint-keyed profile; every later process (and every
        # later corpus in this one) loads it with zero probe dispatches.
        # Must run BEFORE the budget re-reads below — they resolve
        # env > profile > seeded.  ensure_calibrated never raises.
        from nemo_tpu.platform import profile as _platform_profile

        _platform_profile.ensure_calibrated()
        # Full state reset: a backend instance may be reused across corpora.
        # The giant threshold is re-read here and ONLY here, so _fused and
        # build_figures can never disagree within one corpus.
        self._giant_v = _giant_threshold()
        self._giant_impl = self._resolve_giant_impl()
        self._analysis_impl = self._resolve_analysis_impl()
        self._analysis_host_work = _analysis_host_work_budget()
        self._sparse_device_mem = _sparse_device_mem_bytes()
        self._sparse_device_density = _sparse_device_density()
        self._sparse_device_min_v = _sparse_device_min_v()
        self._synth_impl = self._resolve_synth_impl()
        self._synth_host_work = _synth_host_work_budget()
        self.analysis_routes = []
        self._narrow_xfer = self._resolve_narrow_xfer()
        self._max_batch = (
            self.max_batch if self.max_batch is not None else self._resolve_max_batch()
        )
        self._diff_host_work = _diff_host_work_budget()
        #: impl the last _fused giant dispatch actually took (None = no
        #: giant runs in the corpus) — surfaced in the bench giant row.
        self.giant_impl_used = None
        self.molly = molly
        self.vocab = CorpusVocab()
        self.packed = {}
        self.raw = _LazyGraphs(self._build_raw)
        self.clean = _LazyGraphs(self._build_clean)
        self.cond_holds = {}
        self.achieved_pre = {}
        self.simplified = {}
        self._simplified_row = {}
        self._fused_out = None
        self._proto_tables_cache = None
        self._staged_inputs = None
        self._clean_rows = {}
        self._run_by_iter = {r.iteration: r for r in molly.runs}
        nc = getattr(molly, "native_corpus", None)
        self._corpus = nc
        if nc is not None:
            # Packed-first path: the native ETL already produced batch-layout
            # arrays and the interning order is bit-identical to the Python
            # path by construction (native/nemo_native.cpp:ingest), so the
            # vocab rebuilds from the corpus string lists and per-run graphs
            # become lazy array views — no per-graph Python repack.
            for t in nc.tables:
                self.vocab.tables.intern(t)
            for lb in nc.labels:
                self.vocab.labels.intern(lb)
            for tm in nc.times:
                self.vocab.times.intern(tm)
            self._corpus_graphs = CorpusGraphs(nc)
            self._row_by_iter = {int(it): i for i, it in enumerate(nc.iteration)}
            self.packed = _CorpusPacked(self._corpus_graphs, self._row_by_iter)
            # Per-iteration linearity for the fused fast-path gate, built
            # POSITIONALLY so duplicate iteration values (which would make
            # _row_by_iter lossy) AND their flags together — a collision
            # can only force the closure fallback, never a wrong fast path.
            self._lin_by_iter = {}
            for i, it in enumerate(nc.iteration):
                f = bool(nc.pre.chain_linear[i] and nc.post.chain_linear[i])
                self._lin_by_iter[int(it)] = self._lin_by_iter.get(int(it), True) and f
        else:
            self._corpus_graphs = None
            self._row_by_iter = {}
            self._lin_by_iter = {}
            for run in molly.runs:
                for cond, prov in (("pre", run.pre_prov), ("post", run.post_prov)):
                    self.packed[(run.iteration, cond)] = pack_graph(prov, self.vocab)

    def close_db(self) -> None:
        # Release everything init_graph_db allocates (reference: CloseDB,
        # graphing/helpers.go:58-86); the backend stays reusable.  The native
        # corpus handle is NOT closed here: the report writer splices its
        # prov JSON after close_db, and molly owns its lifetime (GC).
        self.molly = None
        self.vocab = None
        self.packed = {}
        self.raw = _LazyGraphs(self._build_raw)
        self.clean = _LazyGraphs(self._build_clean)
        self.cond_holds = {}
        self.achieved_pre = {}
        self.simplified = {}
        self._simplified_row = {}
        self._fused_out = None
        self._proto_tables_cache = None
        self._staged_inputs = None
        self._clean_rows = {}
        self._run_by_iter = {}
        self._corpus = None
        self._corpus_graphs = None
        self._row_by_iter = {}
        self._lin_by_iter = {}

    # ------------------------------------------------------- lazy host graphs

    def _build_raw(self, key: tuple[int, str]) -> PGraph:
        """Materialize one run's raw provenance as a host property-graph,
        with condition_holds mirrored from the kernel output."""
        assert self.molly is not None
        rid, cond = key
        if self._corpus is not None:
            return self._corpus_pgraph(key)
        run = self._run_by_iter[rid]
        g = build_pgraph(run.pre_prov if cond == "pre" else run.post_prov)
        holds = self.cond_holds.get(key)
        if holds is not None:
            pg = self.packed[key]
            for slot in range(pg.n_goals):
                g.nodes[pg.node_ids[slot]].cond_holds = bool(holds[slot])
        return g

    def _corpus_pgraph(self, key: tuple[int, str]) -> PGraph:
        """build_pgraph equivalent over packed corpus arrays: identical node
        insertion order (goals then rules, prov order), identical edge order
        and MERGE dedup — the DOT/query layers see the same graph the Python
        ingest path would have built."""
        from nemo_tpu.graphs.pgraph import PNode

        pg = self.packed[key]
        holds = self.cond_holds.get(key)
        tables, labels, times = self.vocab.tables, self.vocab.labels, self.vocab.times
        ids = [pg.node_ids[s] for s in range(pg.n_nodes)]
        g = PGraph()
        table_l = pg.table_id.tolist()
        label_l = pg.label_id.tolist()
        time_l = pg.time_id.tolist()
        type_l = pg.type_id.tolist()
        for s in range(pg.n_nodes):
            is_goal = s < pg.n_goals
            g.add_node(
                PNode(
                    id=ids[s],
                    is_goal=is_goal,
                    label=labels[label_l[s]],
                    table=tables[table_l[s]],
                    time=times[time_l[s]] if is_goal else "",
                    type="" if is_goal else TYPE_NAMES.get(type_l[s], ""),
                    cond_holds=bool(holds[s]) if (is_goal and holds is not None) else False,
                )
            )
        for s, d in pg.edges.tolist():
            g.add_edge(ids[s], ids[d])
        return g

    def _build_clean(self, key: tuple[int, str]) -> PGraph:
        """Materialize one simplified shadow graph (run 1000+i) from the
        stored simplify-kernel outputs."""
        rid, cond = key
        base_rid = rid - CLEAN_OFFSET
        bi, row = self._simplified_row[(base_rid, cond)]
        batch, adj, alive, type_new = self.simplified[cond][bi]
        holds = self.cond_holds[(base_rid, cond)]
        n = int(batch.n_nodes[row])
        padded_holds = np.zeros(batch.v, dtype=bool)
        padded_holds[:n] = holds
        rows = self._clean_rows.get((base_rid, cond))
        if rows is None:
            # Fallback for callers that bypass pull_pre_post_prov's batched
            # prefetch: three small per-row transfers (the bulk arrays may
            # live on device, LocalExecutor.ON_DEVICE) — never per slot.
            rows = (np.asarray(alive[row]), np.asarray(adj[row]), np.asarray(type_new[row]))
        alive_r, adj_r, type_r = rows
        return unpack_to_pgraph(
            batch,
            row,
            self.vocab,
            alive_r,
            adj_r,
            type_r,
            padded_holds,
            id_prefix=f"run_{rid}_{cond}_",
        )

    def _prefetch_clean_rows(self, run_ids: list[int]) -> None:
        """Materialize the simplify outputs of the given runs host-side with
        ONE gather dispatch per (bucket, array) instead of one transfer per
        row — over the device tunnel (~tens of ms per transfer) per-row
        fetching dominated the figure phase at stress scale."""
        gathers: list[tuple[str, list[tuple[int, int]], tuple]] = []
        for cond in ("pre", "post"):
            by_bucket: dict[int, list[tuple[int, int]]] = {}
            for rid in run_ids:
                loc = self._simplified_row.get((rid, cond))
                if loc is not None and (rid, cond) not in self._clean_rows:
                    by_bucket.setdefault(loc[0], []).append((loc[1], rid))
            for bi, pairs in by_bucket.items():
                _, adj, alive, type_new = self.simplified[cond][bi]
                rows = np.asarray([r for r, _ in pairs])
                # Dispatch every gather before fetching any result: the
                # row-gathers are independent, so their device->host copies
                # overlap into ~1 tunnel RTT (_prefetch_to_host).
                gathers.append((cond, pairs, (alive[rows], adj[rows], type_new[rows])))
        _prefetch_to_host(a for _, _, arrs in gathers for a in arrs)
        for cond, pairs, (alive_g, adj_g, type_g) in gathers:
            alive_g, adj_g, type_g = np.asarray(alive_g), np.asarray(adj_g), np.asarray(type_g)
            for j, (_, rid) in enumerate(pairs):
                self._clean_rows[(rid, cond)] = (alive_g[j], adj_g[j], type_g[j])

    # ------------------------------------------------------------- fused step

    def stream_clone(self) -> "JaxBackend":
        """Fresh instance for the segment-streamed map (analysis/stream.py),
        sharing the executor — and with it the jit/compile caches, the
        remote channel of a ServiceBackend-style executor, and the cost
        table — so per-segment backends pay no per-segment warmup.  Only
        instance state (vocab, packed views, fused outputs) is per-clone;
        init_graph_db/stage_fused_inputs are pure host work, safe on the
        prefetch thread, while dispatches stay on the consuming thread."""
        return type(self)(max_batch=self.max_batch, executor=self.executor)

    def _plan_fused_inputs(self) -> dict:
        """The ``analysis:pack`` section of :meth:`_fused` as a pure
        function of the initialized corpus: the giant split, the stress
        floors, and the bucketized batch pairs.  Factored out so the
        streamed prefetch (stage_fused_inputs) can run it for segment k+1
        on a background thread while segment k's dispatches drain —
        byte-identical inputs either way."""
        assert self.molly is not None
        # Giant-run auto-dispatch: a run whose node count exceeds
        # NEMO_GIANT_V leaves the dense buckets (its [B,V,V] adjacency
        # would dominate or OOM them) and analyzes alone on the
        # node-sharded closure-free path (parallel/giant.py).
        giant_v = self._giant_v
        if self._corpus is not None:
            # Packed-first: node counts come from the corpus arrays —
            # never materialize 2N lazy graph views just to size-split.
            nc = self._corpus
            nmax = np.maximum(nc.pre.n_nodes, nc.post.n_nodes)
            rows = np.nonzero(nmax <= giant_v)[0].tolist()
            giant_ids = [int(nc.iteration[i]) for i in np.nonzero(nmax > giant_v)[0]]
            n_dense = len(rows)
            run_ids = None
        else:
            run_ids, giant_ids = [], []
            for r in self.molly.runs:
                n = max(
                    self.packed[(r.iteration, "pre")].n_nodes,
                    self.packed[(r.iteration, "post")].n_nodes,
                )
                (giant_ids if n > giant_v else run_ids).append(r.iteration)
            n_dense = len(run_ids)
        # Static dims round to powers of two (see graphs_to_step) so
        # corpora with nearby vocab sizes share compiled programs; at
        # stress scale, size FLOORS collapse the per-family bucket
        # variance entirely — padding [B,64,64] instead of [B,32,32]
        # costs milliseconds of extra MXU work, while each extra
        # compiled program costs ~10s of TPU compile.  The diff tail is
        # excluded (with_diff=0): the backend diffs against the chosen
        # good run in its own dispatch, and dropping it removes the
        # label vocab (the most corpus-varying dim) from the signature.
        big = n_dense >= 512
        # min_d floors the depth-bucket: per-family corpus depths (15-19
        # across the case studies) otherwise bucket to 16 vs 32 and split
        # an identical shape into two compiled programs; with the floor
        # (and the pinned pre/post table ids) every big corpus shares
        # ONE fused program — each extra program costs tens of seconds
        # of fresh TPU compile, the extra trip counts cost microseconds.
        floors = (64, 256, 32, 32) if big else (16, 16, 8, 4)
        min_v, min_e, _min_t, _min_d = floors
        # The pack span splits load_raw_provenance's wall into bucket
        # construction vs routed analysis (the ISSUE 3 profiling ask):
        # at 1x the phase was 5-7 s of the 9.2 s e2e wall, and the
        # span shows the analysis dispatch — not this packing — is the
        # dominant term, which is what the sparse route removes.
        # The shard multiple folds into the bucketizer's run-axis pad
        # (ROADMAP 3b / ISSUE 10 satellite): batches leave here already
        # a multiple of the run-mesh width, so pad_place_named_arrays
        # places without copying on the hot path.  Resolved by the
        # process that owns the device; RemoteExecutor deployments pad
        # again sidecar-side if the meshes disagree (rare, harmless).
        from nemo_tpu.parallel.mesh import shard_device_count

        shard_mult = shard_device_count()
        with obs.span("analysis:pack", runs=n_dense):
            if self._corpus is not None:
                batches = bucketize_pairs_corpus(
                    self._corpus_graphs,
                    rows,
                    self._corpus.iteration,
                    self._max_batch,
                    min_v=min_v,
                    min_e=min_e,
                    shard_multiple=shard_mult,
                )
            else:
                pre = [self.packed[(i, "pre")] for i in run_ids]
                post = [self.packed[(i, "post")] for i in run_ids]
                batches = bucketize_pairs(
                    run_ids, pre, post, self._max_batch, min_v=min_v,
                    min_e=min_e, shard_multiple=shard_mult,
                )
        return {
            "batches": batches,
            "giant_ids": giant_ids,
            "n_dense": n_dense,
            "floors": floors,
        }

    def stage_fused_inputs(self) -> dict:
        """Pre-compute (and, where a real accelerator backs the default
        platform, device-stage) the fused dispatch inputs — the host half
        of the double-buffered stream pipeline (ISSUE 12).  Called on the
        prefetch thread after init_graph_db; the next :meth:`_fused` on
        this instance adopts the plan instead of re-bucketizing.  Device
        staging narrows exactly as the dispatch would and ``jax.device_put``s
        the planes so the dispatch-time H2D copy is already in flight; it
        is skipped on CPU (host "transfers" are free) and under an active
        run mesh (pad_place_named_arrays owns placement there).  Returns
        the plan (exposing ``staged_bytes`` for the stream metrics)."""
        plan = self._plan_fused_inputs()
        staged_bytes = 0
        from nemo_tpu.parallel.mesh import shard_plan

        if jax.default_backend() != "cpu" and not shard_plan()[0]:
            _, _, min_t, _ = plan["floors"]
            num_tables = bucket_size(len(self.vocab.tables), min_t)
            staged: dict[int, dict] = {}
            for bi, (pre_b, post_b) in enumerate(plan["batches"]):
                arrays = _narrow_fused_arrays(
                    _verb_arrays(pre_b, post_b),
                    v=pre_b.v,
                    num_tables=num_tables,
                    with_diff=False,
                    narrow=self._narrow_xfer,
                )
                staged[bi] = {k: jax.device_put(a) for k, a in arrays.items()}
                staged_bytes += sum(
                    getattr(a, "nbytes", 0) for a in arrays.values()
                )
            plan["staged_arrays"] = staged
        plan["staged_bytes"] = staged_bytes
        self._staged_inputs = plan
        return plan

    def _fused(self) -> list[tuple[PackedBatch, PackedBatch, dict[str, np.ndarray]]]:
        """Run the fused analysis step once per joint size bucket; cached.

        This is the backend's ONLY batched device work: one dispatch per
        bucket computes condition marking, simplification, and prototype
        bitsets for both conditions of every run — the same analysis_step
        the benchmark times and the sidecar serves, replacing the reference's
        per-run, per-phase Cypher round-trips (main.go:106-180)."""
        if self._fused_out is None:
            assert self.molly is not None
            # Bucketize — or adopt the plan a streamed prefetch already
            # staged on the background thread (stage_fused_inputs): the
            # host-side pack work then overlaps the PREVIOUS segment's
            # dispatches instead of serializing ahead of this one's.
            plan = self._staged_inputs
            self._staged_inputs = None
            if plan is None:
                plan = self._plan_fused_inputs()
            batches = plan["batches"]
            giant_ids = plan["giant_ids"]
            n_dense = plan["n_dense"]
            min_v, min_e, min_t, min_d = plan["floors"]
            staged_arrays = plan.get("staged_arrays") or {}
            params_common = dict(
                pre_tid=self.vocab.tables.lookup("pre"),
                post_tid=self.vocab.tables.lookup("post"),
                num_tables=bucket_size(len(self.vocab.tables), min_t),
                num_labels=8,  # unused without the diff tail
                with_diff=0,
            )
            from nemo_tpu.ops.simplify import pair_chains_linear
            from nemo_tpu.parallel import sched as sched_mod

            # Heterogeneous schedule (ISSUE 7 tentpole): every joint bucket
            # becomes a two-lane Job — the (mesh-sharded) fused device
            # dispatch or the sparse CSR host engine compute IDENTICAL
            # results (the parity suites pin that), so the scheduler is
            # free to run both tiers concurrently and steal across them.
            # PR 3's crossover survives two ways: forced/platform routes
            # PIN their lane (an operator decision, not a preference), and
            # the unmeasured cost model is seeded to cross at the same
            # work budget — feedback from measured walls takes over within
            # a session (parallel/sched.py).
            jobs: list = []
            serial_plan: list[tuple[str, str]] = []  # (lane, reason) sans scheduler

            # Whether the sparse-device lane is schedulable for UNPINNED
            # fused jobs: forced routes pin it regardless; the cost-model
            # mixing (dense-device / sparse-device / sparse-host per
            # bucket, ISSUE 10) engages only where a real accelerator
            # backs both device lanes — on a CPU fallback the sparse HOST
            # engine strictly dominates XLA:CPU scatter waves, so offering
            # the lane there would only invite mispredicted steals.
            sparse_dev_lanes = (
                self._analysis_impl in ("auto", "crossover")
                and jax.default_backend() != "cpu"
            )

            def _add_fused_job(pre_b, post_b, linear, bi):
                n_rows = len(pre_b.run_ids)

                def dispatch_arrays():
                    # A streamed prefetch may have already narrowed (and,
                    # on a real accelerator, device_put) this bucket's verb
                    # planes — dispatch those instead of rebuilding them.
                    staged = staged_arrays.get(bi)
                    if staged is not None:
                        return staged
                    return _narrow_fused_arrays(
                        _verb_arrays(pre_b, post_b),
                        v=pre_b.v,
                        num_tables=params_common["num_tables"],
                        with_diff=False,
                        narrow=self._narrow_xfer,
                    )

                route, reason, work = self._analysis_route(
                    n_rows, pre_b.v, pre_b.e,
                    rows_dispatch=int(pre_b.is_goal.shape[0]),
                )
                lane = sched_mod.LANE_OF_ROUTE[route]
                # "mem" pins like the forced/platform reasons: a bucket
                # past the dense memory watermark must never be stolen
                # onto the dense device lane (it would OOM exactly where
                # the route said it would); the breaker/failover machinery
                # may still reroute it to the bit-identical host lane.
                pinned = lane if reason in ("forced", "platform", "mem") else None
                job = sched_mod.Job(
                    index=len(jobs),
                    verb="fused",
                    rows=n_rows,
                    v=pre_b.v,
                    e=pre_b.e,
                    work=work,
                    execute=None,  # assigned below (the closure marks `job`)
                    pinned=pinned,
                    reason=reason,
                    lanes=(
                        ("device", "sparse_device", "host")
                        if sparse_dev_lanes or route == "sparse_device"
                        else ("device", "host")
                    ),
                    rows_dispatch=int(pre_b.is_goal.shape[0]),
                )

                def execute(run_lane, rec_reason, stolen):
                    rec = self._record_route(
                        "fused",
                        sched_mod.ROUTE_OF_LANE[run_lane],
                        n_rows,
                        pre_b.v,
                        pre_b.e,
                        work,
                        rec_reason,
                    )
                    if run_lane == "host":
                        from nemo_tpu.ops.sparse_host import sparse_analysis_step

                        # Counted under the same kernel.dispatches.* prefix
                        # as the device verbs: the result cache's
                        # zero-dispatch assertion (analysis/delta.py:
                        # kernel_dispatch_count) sums the prefix, so a
                        # sparse-routed recompute can never masquerade as a
                        # cache hit.
                        obs.metrics.inc("kernel.dispatches.sparse_fused")
                        with obs.span("analysis:route", **rec):
                            with obs.span(
                                "kernel:fused", impl="sparse_host", rows=n_rows
                            ):
                                res = sparse_analysis_step(
                                    pre_b,
                                    post_b,
                                    v=pre_b.v,
                                    pre_tid=params_common["pre_tid"],
                                    post_tid=params_common["post_tid"],
                                    num_tables=params_common["num_tables"],
                                    comp_linear=linear,
                                )
                        return (pre_b, post_b, res)
                    if run_lane == "sparse_device":
                        # Sparse-CSR DEVICE step (ISSUE 10): the same
                        # executor boundary (RemoteExecutor ships the same
                        # [B,E] planes over the Kernel RPC — never a dense
                        # [B,V,V] — so the upload-narrowing savings
                        # compound), clean adjacency returned as a
                        # contracted edge list and densified lazily per
                        # figure-selected row.
                        with obs.span("analysis:route", **rec):
                            res = self.executor.run(
                                "sparse_fused",
                                dispatch_arrays(),
                                dict(
                                    v=pre_b.v,
                                    pre_tid=params_common["pre_tid"],
                                    post_tid=params_common["post_tid"],
                                    num_tables=params_common["num_tables"],
                                    comp_linear=int(linear),
                                ),
                                rows=n_rows,
                            )
                        res = _wrap_sparse_clean(res, pre_b.v)
                        if getattr(self.executor, "last_dispatch_compiled", False):
                            job.wall_tainted = True
                        return (pre_b, post_b, res)
                    with obs.span("analysis:route", **rec):
                        res = self.executor.run(
                            "fused",
                            dispatch_arrays(),
                            dict(
                                v=pre_b.v,
                                max_depth=bucket_size(
                                    max(pre_b.max_depth, post_b.max_depth), min_d
                                ),
                                comp_linear=int(linear),
                                **params_common,
                            ),
                            rows=n_rows,
                        )
                    # Compile walls must not feed the scheduler's warm-cost
                    # EWMA (they are one-off; a RemoteExecutor has no flag
                    # and its server-side compiles stay unmarked — the EWMA
                    # absorbs those over a session).
                    if getattr(self.executor, "last_dispatch_compiled", False):
                        job.wall_tainted = True
                    return (pre_b, post_b, res)

                job.execute = execute
                jobs.append(job)
                serial_plan.append((lane, reason))

            for bi, (pre_b, post_b) in enumerate(batches):
                # Linear-chain fast path: when every run's @next member
                # subgraph is a verified linear chain, the device step
                # labels components by O(V log V) pointer doubling instead
                # of all-pairs closures — ~2/3 of the fused step's V^3 work.
                # On the packed-first path the per-run flags were computed
                # by the C++ engine at parse time (graph_chain_linear);
                # otherwise the O(B*(V+E)) host bincounts run per bucket.
                if self._corpus is not None:
                    linear = all(self._lin_by_iter[i] for i in pre_b.run_ids)
                else:
                    linear = pair_chains_linear(pre_b, post_b)
                _add_fused_job(pre_b, post_b, linear, bi)
            if giant_ids:
                from nemo_tpu.parallel.giant import giant_plan, pad_comp_labels

                # Corpus-common giant buckets + power-of-two depth buckets:
                # the giant program's jit key is (V, E, depths, ...), so
                # per-run raw values would compile one program per giant run
                # (tens of seconds each on TPU) — bucketing shares one
                # program across the corpus's giants at the cost of a few
                # extra masked iterations.
                g_graphs = [
                    (self.packed[(rid, "pre")], self.packed[(rid, "post")])
                    for rid in giant_ids
                ]
                v_g = bucket_size(max(g.n_nodes for pair in g_graphs for g in pair))
                e_g = bucket_size(
                    max(1, *(len(g.edges) for pair in g_graphs for g in pair))
                )
                # Crossover routing (VERDICT r4 task 2): "host" runs the
                # exact sparse O(V+E) numpy analysis instead of the dense
                # node-sharded device kernels — the dense path on a CPU
                # fallback is 5-6x slower than even the sequential oracle
                # (BENCH_r04: 87.4 s vs 14.3 s), the same inversion the
                # diff crossover fixed one verb over.  Resolved per corpus
                # in init_graph_db (_giant_impl_default).
                self.giant_impl_used = self._giant_impl
                giant_lane = {
                    "host": "host",
                    "sparse_device": "sparse_device",
                }.get(self._giant_impl, "device")
                for rid, (gpre, gpost) in zip(giant_ids, g_graphs):
                    g_job = sched_mod.Job(
                        index=len(jobs),
                        verb="giant",
                        rows=1,
                        v=v_g,
                        e=e_g,
                        work=v_g + e_g,
                        execute=None,  # assigned below (the closure marks it)
                        pinned=giant_lane,
                        reason="giant_impl",
                        rows_dispatch=1,  # giants pack B=1, no run-axis pad
                    )

                    def g_execute(run_lane, rec_reason, stolen, gpre=gpre, gpost=gpost, rid=rid, job=g_job):
                        pre_b = pack_batch([rid], [gpre], v_g, e_g)
                        post_b = pack_batch([rid], [gpost], v_g, e_g)
                        lin_pre, depth_pre, lab_pre = giant_plan(gpre)
                        lin_post, depth_post, lab_post = giant_plan(gpost)
                        pre_labels = pad_comp_labels(lab_pre, gpre.n_nodes, v_g)
                        post_labels = pad_comp_labels(lab_post, gpost.n_nodes, v_g)
                        # Route record for the giant verb: "host" is the
                        # sparse side of this crossover, "device" the dense
                        # one — one uniform sparse/dense vocabulary across
                        # all verbs.
                        rec = self._record_route(
                            "giant",
                            sched_mod.ROUTE_OF_LANE[run_lane],
                            1,
                            v_g,
                            e_g,
                            v_g + e_g,
                            rec_reason,
                        )
                        if run_lane == "host":
                            from nemo_tpu.parallel.giant import giant_analysis_host

                            obs.metrics.inc("kernel.dispatches.sparse_giant")
                            with obs.span("analysis:route", **rec):
                                res = giant_analysis_host(
                                    pre_b,
                                    post_b,
                                    pre_tid=params_common["pre_tid"],
                                    post_tid=params_common["post_tid"],
                                    num_tables=params_common["num_tables"],
                                    pre_labels=pre_labels,
                                    post_labels=post_labels,
                                )
                            return (pre_b, post_b, res)
                        if run_lane == "sparse_device":
                            # Giant-V on the DEVICE, sparsely (ISSUE 10):
                            # the CSR step's O(V+E) frontier waves replace
                            # the node-sharded dense kernels — no [V,V]
                            # adjacency, no closure labeling (the fix-point
                            # min-label relaxation is exact for any member
                            # structure, so giant_plan's union-find labels
                            # need not ship).
                            with obs.span("analysis:route", **rec):
                                res = self.executor.run(
                                    "sparse_fused",
                                    _narrow_fused_arrays(
                                        _verb_arrays(pre_b, post_b),
                                        v=v_g,
                                        num_tables=params_common["num_tables"],
                                        with_diff=False,
                                        narrow=self._narrow_xfer,
                                    ),
                                    dict(
                                        v=v_g,
                                        pre_tid=params_common["pre_tid"],
                                        post_tid=params_common["post_tid"],
                                        num_tables=params_common["num_tables"],
                                        comp_linear=int(lin_pre and lin_post),
                                    ),
                                    rows=1,
                                )
                            res = _wrap_sparse_clean(res, v_g)
                            if getattr(self.executor, "last_dispatch_compiled", False):
                                job.wall_tainted = True
                            return (pre_b, post_b, res)
                        arrays = _verb_arrays(pre_b, post_b)
                        arrays["pre_comp_labels"] = pre_labels
                        arrays["post_comp_labels"] = post_labels
                        with obs.span("analysis:route", **rec):
                            res = self.executor.run(
                                "giant",
                                arrays,
                                dict(
                                    v=v_g,
                                    pre_tid=params_common["pre_tid"],
                                    post_tid=params_common["post_tid"],
                                    num_tables=params_common["num_tables"],
                                    max_depth=bucket_size(
                                        max(pre_b.max_depth, post_b.max_depth), 4
                                    ),
                                    comp_linear=int(lin_pre and lin_post),
                                    proto_depth=bucket_size(
                                        max(depth_pre, depth_post), 8
                                    ),
                                ),
                                rows=1,
                            )
                        if getattr(self.executor, "last_dispatch_compiled", False):
                            job.wall_tainted = True
                        return (pre_b, post_b, res)

                    # Giant jobs PIN their per-corpus resolved lane: the
                    # crossover there is a platform inversion (dense giant
                    # on a CPU fallback is 5-6x slower than the oracle),
                    # not a preference the cost model may override.
                    g_job.execute = g_execute
                    jobs.append(g_job)
                    serial_plan.append((giant_lane, "giant_impl"))
            # Drain: the two-lane work-stealing scheduler overlaps the
            # device and host tiers (NEMO_SCHED auto/on); off — or a
            # single-job corpus, where concurrency has nothing to overlap —
            # keeps the exact serial pre-scheduler loop.  Results land in
            # job order either way, so bucket order (and with it every
            # downstream row index) is schedule-independent.
            mode = sched_mod.sched_env()
            if mode != "off" and (mode == "on" or len(jobs) > 1):
                scheduler = sched_mod.HeterogeneousScheduler(
                    sched_mod.session_models(
                        self._analysis_host_work, sched_device_hint
                    )
                )
                out = scheduler.run(jobs)
            else:
                out = [
                    job.execute(lane, reason, False)
                    for job, (lane, reason) in zip(jobs, serial_plan)
                ]
            self._fused_out = out
        return self._fused_out

    # ------------------------------------------------------------------- load

    def load_raw_provenance(self) -> None:
        assert self.molly is not None
        # Lazy per-run views (ISSUE 12): the fused bucket outputs are
        # indexed once and a run's holds/achieved rows materialize only
        # when a consumer touches them — figure-selected property-graph
        # builds and the good run's diff backdrop, a policy-bounded
        # handful — instead of the old corpus-wide per-run slicing loop.
        # Host property-graphs already mirror these lazily on first access
        # (_build_raw), so this phase's wall is now the fused dispatch
        # alone (VERDICT r1).
        fused = self._fused()
        self.cond_holds = _LazyCondHolds(fused)
        self.achieved_pre = _LazyAchievedPre(fused)
        # Any raw property-graph built BEFORE this point lacks cond_holds
        # styling; drop the lazy cache so those rebuild with holds mirrored
        # (ADVICE r2: the cache must not pin an order-dependent invariant).
        self.raw = _LazyGraphs(self._build_raw)

    # --------------------------------------------------------------- simplify

    def simplify_prov(self, iters: list[int]) -> None:
        # The fused step simplifies every run; this phase just registers the
        # shadow-graph rows for the requested iterations (per-run outputs are
        # independent, so computing all rows is semantically identical).
        want = set(iters)
        for cond in ("pre", "post"):
            outs = []
            for pre_b, post_b, res in self._fused():
                b = pre_b if cond == "pre" else post_b
                bi = len(outs)
                outs.append((b, res[f"{cond}_adj_clean"], res[f"{cond}_alive"], res[f"{cond}_type"]))
                for row, rid in enumerate(b.run_ids):
                    if rid in want:
                        self._simplified_row[(rid, cond)] = (bi, row)
            self.simplified[cond] = outs

    # (create_hazard_analysis is inherited from GraphBackend — host-side only.)

    # ------------------------------------------------------------- prototypes

    def _proto_tables_by_run(self) -> tuple[dict[int, list[str]], dict[int, set[str]]]:
        """Slice the fused step's prototype outputs per run; returns
        (ordered qualifying tables per run, all present rule tables per
        run).  Memoized per corpus (reset in init_graph_db/close_db):
        callers treat the dicts as read-only, and the synthesis phase
        (ISSUE 13) re-reads the good run's tables after the prototypes
        phase already extracted the whole view — without the memo that
        second call would repeat the corpus-wide lexsort extraction
        (~seconds at 102k runs) to fetch one run's list."""
        if self._proto_tables_cache is not None:
            return self._proto_tables_cache
        ordered: dict[int, list[str]] = {}
        present: dict[int, set[str]] = {}
        names = np.asarray(self.vocab.tables.strings, dtype=object)
        for _, post_b, res in self._fused():
            bits, min_depth, present_bits = (
                np.asarray(res["proto_bits"]),
                np.asarray(res["proto_min_depth"]),
                np.asarray(res["proto_present"]),
            )
            # Vectorized per-bucket extraction (the per-row Python loop was
            # host-linear at stress scale — ~seconds over 102k runs): one
            # lexsort orders qualifying (row, depth, name) triples exactly
            # like the old per-row sorted(tabs) — depth first, table name
            # as tiebreak — then row boundaries split the flat list.
            nm = names[: bits.shape[1]]
            rows, ts = np.nonzero(bits & (min_depth < DEPTH_INF))
            order = np.lexsort((nm[ts], min_depth[rows, ts], rows))
            rows_o, names_o = rows[order], nm[ts[order]]
            starts = np.searchsorted(rows_o, np.arange(bits.shape[0] + 1))
            p_rows, p_ts = np.nonzero(present_bits)
            p_starts = np.searchsorted(p_rows, np.arange(bits.shape[0] + 1))
            p_names = nm[p_ts]
            for row, rid in enumerate(post_b.run_ids):
                ordered[rid] = list(names_o[starts[row] : starts[row + 1]])
                present[rid] = set(p_names[p_starts[row] : p_starts[row + 1]])
        self._proto_tables_cache = (ordered, present)
        return self._proto_tables_cache

    def create_prototypes(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[list[str], list[list[str]], list[str], list[list[str]]]:
        ordered, present = self._proto_tables_by_run()
        per_run = [ordered.get(i, []) for i in success_iters]
        inter = intersect_proto(per_run, "post")
        union = union_proto(per_run, "post")
        inter_miss = [missing_from(inter, present.get(f, set())) for f in failed_iters]
        union_miss = [missing_from(union, present.get(f, set())) for f in failed_iters]
        return wrap_code(inter), inter_miss, wrap_code(union), union_miss

    def proto_tables_by_run(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[dict[int, list[str]], dict[int, set[str]]]:
        # The same fused-step slices create_prototypes consumes, exposed
        # per run so the pipeline's reduce can merge across store segments.
        ordered, present = self._proto_tables_by_run()
        return (
            {i: ordered.get(i, []) for i in success_iters},
            {f: present.get(f, set()) for f in failed_iters},
        )

    # ------------------------------------------------------------------- pull

    def pull_pre_post_prov(
        self, iters: list[int] | None = None
    ) -> tuple[list[DotGraph], list[DotGraph], list[DotGraph], list[DotGraph]]:
        assert self.molly is not None
        run_ids = [r.iteration for r in self.molly.runs] if iters is None else list(iters)
        self._prefetch_clean_rows(run_ids)
        pre, post, pre_clean, post_clean = [], [], [], []
        for i in run_ids:
            pre.append(create_dot(self.raw[(i, "pre")], "pre"))
            post.append(create_dot(self.raw[(i, "post")], "post"))
            pre_clean.append(create_dot(self.clean[(CLEAN_OFFSET + i, "pre")], "pre"))
            post_clean.append(create_dot(self.clean[(CLEAN_OFFSET + i, "post")], "post"))
        return pre, post, pre_clean, post_clean

    # ------------------------------------------------------------------- diff

    def create_naive_diff_prov(
        self,
        symmetric: bool,
        failed_iters: list[int],
        success_post_dot: DotGraph,
        dot_iters: list[int] | None = None,
    ) -> tuple[list[DotGraph], list[DotGraph], list[list[MissingEvent]]]:
        assert self.molly is not None
        if not failed_iters:
            return [], [], []
        dot_set = set(failed_iters if dot_iters is None else dot_iters)
        g = self.good_run_iter()
        good = self.packed[(g, "post")]
        # Pad the single good graph to its own bucket; pad the failed-run
        # axis and label/table dims to powers of two so corpora with nearby
        # failure counts share one compiled diff program (padding rows have
        # all-false label bitsets and are sliced away below).
        num_labels = bucket_size(max(1, len(self.vocab.labels)), 8)
        gb = pack_batch([g], [good])

        bits = np.zeros((bucket_size(max(1, len(failed_iters)), 8), num_labels), dtype=bool)
        if self._corpus is not None:
            # Packed-first: one vectorized scatter over the corpus arrays
            # instead of a per-failed-run view materialization (is_goal is
            # exactly the slots-below-n_goals mask the legacy slice takes).
            rows = np.asarray([self._row_by_iter[f] for f in failed_iters], dtype=np.int64)
            isg = self._corpus.post.is_goal[rows]
            lab = self._corpus.post.label_id[rows]
            j_idx, s_idx = np.nonzero(isg)
            bits[j_idx, lab[j_idx, s_idx]] = True
        else:
            for j, f in enumerate(failed_iters):
                pg = self.packed[(f, "post")]
                goal_labels = pg.label_id[: pg.n_goals]
                bits[j, goal_labels] = True

        # Routing (VERDICT r3 task 3): giant good runs MUST take the sparse
        # host path (dense V^3 closure prohibitive); small jobs TAKE it
        # because it wins — below the measured work crossover a single
        # tunnel dispatch costs more than the whole exact host computation.
        # An EXPLICIT NEMO_ANALYSIS_IMPL forces the verb both ways (the
        # parity suites drive both sides through one knob).  On auto, a
        # backend whose route resolved to sparse (the CPU fallback) sends
        # diff host-side regardless of size — the dense diff dispatch on
        # XLA:CPU is the same wrong-algorithm case as the fused buckets;
        # otherwise the measured NEMO_DIFF_HOST_WORK crossover decides —
        # diff's own device-vs-host economics.
        host_work = len(failed_iters) * (good.n_nodes + len(good.edges))
        umbrella = _analysis_impl_env()
        if good.n_nodes > self._giant_v:
            route, route_reason = "sparse", "giant"
        elif umbrella in ("sparse", "dense", "sparse_device"):
            route, route_reason = umbrella, "forced"
        elif self._analysis_impl == "sparse":
            route, route_reason = "sparse", "platform"
        elif host_work <= self._diff_host_work:
            route, route_reason = "sparse", "crossover"
        elif (
            self._analysis_impl in ("auto", "crossover")
            and self._sparse_device_mem
            and bits.shape[0] * gb.v * gb.v > self._sparse_device_mem
        ):
            # The dense diff materializes edge_keep [F,V,V] planes; past
            # the dense memory watermark the device stays sparse (the same
            # guard as the fused route's "mem" reason, ISSUE 10).  Gated on
            # auto/crossover — a resolved impl (incl. the ServiceBackend's
            # auto->dense wire-compat resolution: a deployed sidecar one
            # release behind has no sparse_diff verb) skipped the fused
            # route's mem check and must skip this one too.
            route, route_reason = "sparse_device", "mem"
        else:
            route, route_reason = "dense", "crossover"
        rec = self._record_route(
            "diff",
            route,
            len(failed_iters),
            good.n_nodes,
            len(good.edges),
            host_work,
            route_reason,
        )
        sparse_edges = None
        if failed_iters and route == "sparse_device":
            # Sparse-CSR DEVICE diff (ISSUE 10): same waves as the host
            # path, batched over the failed runs on device; edge_keep comes
            # back as a mask over the padded edge list — sliced to the real
            # edges so the sparse-edge consumers below apply unchanged.
            with obs.span("analysis:route", **rec):
                out = self.executor.run(
                    "sparse_diff",
                    {
                        "edge_src": gb.edge_src[0],
                        "edge_dst": gb.edge_dst[0],
                        "edge_mask": gb.edge_mask[0],
                        "is_goal": gb.is_goal[0],
                        "node_mask": gb.node_mask[0],
                        "label_id": gb.label_id[0],
                        "fail_bits": bits,
                    },
                    {"v": gb.v},
                    rows=len(failed_iters),
                )
            node_keep = out["node_keep"]
            edge_keep = out["edge_keep"][:, : len(good.edges)]
            frontier_rule = out["frontier_rule"]
            missing_goal = out["missing_goal"]
            sparse_edges = good.edges
        elif failed_iters and route == "sparse":
            # Sparse host diff: O(F * (V + E)) on the packed edge list and
            # exact (ops/diff.py:diff_masks_host).  edge_keep comes back as
            # a mask over `good.edges`, densified only for figure-selected
            # runs.
            from nemo_tpu.ops.diff import diff_masks_host

            padded_goal = np.zeros(gb.v, dtype=bool)
            padded_goal[: good.n_goals] = True
            padded_label = np.full(gb.v, -1, dtype=np.int64)
            padded_label[: good.n_nodes] = good.label_id
            # Only the real failed-run rows: the padding rows exist for the
            # dense path's compile sharing, which the host path doesn't
            # have — an all-false row would cost a full-graph diff each.
            obs.metrics.inc("kernel.dispatches.sparse_diff")
            with obs.span("analysis:route", **rec):
                node_keep, edge_keep, frontier_rule, missing_goal = diff_masks_host(
                    good.edges, gb.v, padded_goal, padded_label, bits[: len(failed_iters)]
                )
            sparse_edges = good.edges
        elif failed_iters:
            with obs.span("analysis:route", **rec):
                out = self.executor.run(
                    "diff",
                    {
                        "edge_src": gb.edge_src,
                        "edge_dst": gb.edge_dst,
                        "edge_mask": gb.edge_mask,
                        "is_goal": gb.is_goal[0],
                        "node_mask": gb.node_mask[0],
                        "label_id": gb.label_id[0],
                        "fail_bits": bits,
                    },
                    {"v": gb.v, "max_depth": bucket_size(gb.max_depth, 4)},
                )
            node_keep, edge_keep, frontier_rule, missing_goal = (
                out["node_keep"],
                out["edge_keep"],
                out["frontier_rule"],
                out["missing_goal"],
            )
        diff_dots, failed_dots, missing_events = [], [], []
        holds = np.zeros(gb.v, dtype=bool)
        holds[: good.n_nodes] = self.cond_holds[(g, "post")]

        def dense_ek(j: int) -> np.ndarray:
            """edge_keep of run j as dense [V,V] (the sparse host path and
            the device-resident dense plane both densify on demand — only
            figure-selected runs pay the full-plane transfer)."""
            if sparse_edges is None:
                return np.asarray(edge_keep[j])
            dense = np.zeros((gb.v, gb.v), dtype=bool)
            kept = sparse_edges[edge_keep[j]]
            if len(kept):
                dense[kept[:, 0], kept[:, 1]] = True
            return dense

        def children_fn(j: int):
            if sparse_edges is None:
                return lambda r: edge_keep[j][r]
            kept = sparse_edges[edge_keep[j]]

            def children(r: int) -> np.ndarray:
                row = np.zeros(gb.v, dtype=bool)
                sel = kept[kept[:, 0] == r]
                if len(sel):
                    row[sel[:, 1]] = True
                return row

            return children

        for j, f in enumerate(failed_iters):
            prefix = f"run_{DIFF_OFFSET + f}_post_"
            # Missing events ship in debugging.json for EVERY failed run; the
            # overlay DOTs materialize only for runs the figure policy shows.
            missing = self._missing_events(
                gb, frontier_rule[j], missing_goal[j], children_fn(j), prefix, holds
            )
            missing_events.append(missing)
            if f not in dot_set:
                continue
            diff_graph = unpack_to_pgraph(
                gb,
                0,
                self.vocab,
                node_keep[j],
                dense_ek(j),
                gb.type_id[0],
                holds,
                id_prefix=prefix,
            )
            diff_dot, failed_dot = create_diff_dot(
                DIFF_OFFSET + f, diff_graph, self.raw[(f, "post")], g, success_post_dot, missing
            )
            diff_dots.append(diff_dot)
            failed_dots.append(failed_dot)
        return diff_dots, failed_dots, missing_events

    def _missing_events(
        self,
        gb: PackedBatch,
        frontier_rule: np.ndarray,
        missing_goal: np.ndarray,
        children,  # callable(slot) -> [V] bool kept-edge children of slot
        prefix: str,
        holds: np.ndarray,
    ) -> list[MissingEvent]:
        good = gb.graphs[0]

        def rename(slot: int) -> str:
            return rewrite_run_prefix(good.node_ids[slot], prefix)

        out = []
        for r in sorted(np.nonzero(frontier_rule)[0].tolist(), key=rename):
            rule = Rule(
                id=rename(r),
                label=self.vocab.labels[int(good.label_id[r])],
                table=self.vocab.tables[int(good.table_id[r])],
                type={0: "", 1: "async", 2: "next", 3: "collapsed"}[int(good.type_id[r])],
            )
            goals = []
            for gslot in sorted(
                np.nonzero(children(r) & missing_goal)[0].tolist(), key=rename
            ):
                goals.append(
                    Goal(
                        id=rename(gslot),
                        label=self.vocab.labels[int(good.label_id[gslot])],
                        table=self.vocab.tables[int(good.table_id[gslot])],
                        time=self.vocab.times[int(good.time_id[gslot])],
                        cond_holds=bool(holds[gslot]),
                    )
                )
            out.append(MissingEvent(rule=rule, goals=goals))
        return out

    # ------------------------------------------------------------ corrections

    def generate_corrections(self) -> list[str]:
        g = self.good_run_iter()
        return synthesize_corrections(
            find_pre_triggers(self.raw[(g, "pre")]), find_post_triggers(self.raw[(g, "post")])
        )

    # ------------------------------------------------------------- extensions

    def achieved_pre_goal_counts(self) -> dict[int, int]:
        assert self.molly is not None
        pre_tid = self.vocab.tables.lookup("pre")
        # One vectorized reduction per fused bucket (equivalent to the
        # per-run holds[:n_goals] & table==pre sum: is_goal is exactly the
        # slots-below-n_goals mask, and padding rows are all-False).
        counts: dict[int, int] = {}
        for pre_b, _post_b, res in self._fused():
            holds = np.asarray(res["pre_holds"])
            k = len(pre_b.run_ids)
            sel = (
                holds[:k]
                & np.asarray(pre_b.is_goal[:k])
                & (np.asarray(pre_b.table_id[:k]) == pre_tid)
            )
            per_run = sel.sum(axis=1)
            for row, rid in enumerate(pre_b.run_ids):
                counts[rid] = counts.get(rid, 0) + int(per_run[row])
        return counts

    def extension_suggestions(self) -> list[str]:
        return synthesize_extensions(
            extension_candidates(self.raw[(self.baseline_run_iter(), "pre")])
        )

    # -------------------------------------------------------------- synthesis

    def synth_candidates(self, iters: list[int]) -> dict[int, list[str]]:
        """Per-run extension-candidate tables for the corpus-ranked repair
        synthesis (ISSUE 13), batched over the SAME fused buckets the
        analysis verbs ride: one ``synth_ext`` dispatch (or one host
        bincount pass) per bucket extracts every run's candidates at once,
        routed per bucket by NEMO_SYNTH_IMPL / the NEMO_SYNTH_HOST_WORK
        crossover and drained through the heterogeneous scheduler
        (parallel/sched.py — device/host lanes, cost hints, stealing,
        breaker failover) exactly like the fused jobs.  Every dispatch
        records an ``analysis.route.synth.<route>`` decision.  The per-run
        PGraph walk survives as NEMO_SYNTH_IMPL=python — the parity
        ORACLE, one graph at a time (the pre-batching reference path)."""
        assert self.molly is not None
        want = set(iters)
        out: dict[int, list[str]] = {i: [] for i in iters}
        if self._synth_impl == "python":
            rec = self._record_route("synth", "python", len(iters), 0, 0, 0, "forced")
            obs.metrics.inc("kernel.dispatches.synth_python")
            with obs.span("analysis:route", **rec):
                for i in iters:
                    out[i] = sorted(set(extension_candidates(self.raw[(i, "pre")])))
            return out

        from nemo_tpu.parallel import sched as sched_mod

        names = np.asarray(self.vocab.tables.strings, dtype=object)
        jobs: list = []
        serial_plan: list[tuple[str, str]] = []
        for pre_b, _post_b, res in self._fused():
            if not any(rid in want for rid in pre_b.run_ids):
                continue
            n_rows = len(pre_b.run_ids)
            holds = np.asarray(res["pre_holds"])
            # The table-bitset width the fused step already used for this
            # bucket — keeps the synth planes aligned with proto_bits and
            # the jit signature bucket-stable.
            num_tables = int(np.asarray(res["proto_bits"]).shape[1])
            route, reason, work = self._synth_route(n_rows, pre_b.v, pre_b.e)
            lane = "host" if route == "sparse" else "device"
            pinned = lane if reason in ("forced", "platform") else None
            job = sched_mod.Job(
                index=len(jobs),
                verb="synth_ext",  # the cost-model/EWMA shape-class key
                rows=n_rows,
                v=pre_b.v,
                e=pre_b.e,
                work=work,
                execute=None,  # assigned below (the closure marks `job`)
                pinned=pinned,
                reason=reason,
                lanes=("device", "host"),
                rows_dispatch=int(pre_b.is_goal.shape[0]),
            )

            def execute(
                run_lane, rec_reason, stolen,
                pre_b=pre_b, holds=holds, num_tables=num_tables,
                n_rows=n_rows, work=work, job=job,
            ):
                route_name = "sparse" if run_lane == "host" else "sparse_device"
                rec = self._record_route(
                    "synth", route_name, n_rows, pre_b.v, pre_b.e, work, rec_reason
                )
                if run_lane == "host":
                    from nemo_tpu.ops.sparse_host import synth_ext_host

                    # kernel.dispatches.* prefix: the result cache's
                    # zero-dispatch assertion must see host-routed
                    # synthesis recomputes too (the sparse_fused precedent).
                    obs.metrics.inc("kernel.dispatches.synth_host")
                    with obs.span("analysis:route", **rec):
                        bits = synth_ext_host(pre_b, holds, num_tables)
                    return (pre_b, bits)
                with obs.span("analysis:route", **rec):
                    bits = self.executor.run(
                        "synth_ext",
                        {
                            "edge_src": pre_b.edge_src,
                            "edge_dst": pre_b.edge_dst,
                            "edge_mask": pre_b.edge_mask,
                            "is_goal": pre_b.is_goal,
                            "node_mask": pre_b.node_mask,
                            "type_id": pre_b.type_id,
                            "table_id": pre_b.table_id,
                            "holds": holds,
                        },
                        {"v": pre_b.v, "num_tables": num_tables},
                        rows=n_rows,
                    )["ext_bits"]
                if getattr(self.executor, "last_dispatch_compiled", False):
                    job.wall_tainted = True
                return (pre_b, bits)

            job.execute = execute
            jobs.append(job)
            serial_plan.append((lane, reason))

        mode = sched_mod.sched_env()
        if mode != "off" and (mode == "on" or len(jobs) > 1):
            scheduler = sched_mod.HeterogeneousScheduler(
                sched_mod.session_models(self._analysis_host_work, sched_device_hint)
            )
            outs = scheduler.run(jobs)
        else:
            outs = [
                job.execute(lane, reason, False)
                for job, (lane, reason) in zip(jobs, serial_plan)
            ]

        for pre_b, bits in outs:
            bits = np.asarray(bits)
            # Vectorized per-bucket extraction (_proto_tables_by_run's
            # idiom): one lexsort orders (row, name) pairs like the
            # oracle's per-run sorted(set(...)); row boundaries split.
            nm = names[: bits.shape[1]]
            rows_i, ts = np.nonzero(bits)
            order = np.lexsort((nm[ts], rows_i))
            rows_o, names_o = rows_i[order], nm[ts[order]]
            starts = np.searchsorted(rows_o, np.arange(bits.shape[0] + 1))
            for row, rid in enumerate(pre_b.run_ids):
                if rid in want:
                    out[rid] = list(names_o[starts[row] : starts[row + 1]])
        return out

    def generate_extensions(self) -> tuple[bool, list[str]]:
        assert self.molly is not None
        achieved = sum(self.achieved_pre_goal_counts().values())
        all_achieved = achieved >= len(self.molly.runs)
        if all_achieved:
            return True, []
        return False, self.extension_suggestions()
