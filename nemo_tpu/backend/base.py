"""GraphBackend: the framework's central backend interface.

The reference defines this interface implicitly against Neo4j
(main.go:33-44, ten methods); here it is explicit, with two implementations:

  * backend.python_ref.PythonBackend — in-process property-graph oracle that
    mirrors the reference's Cypher semantics exactly; serves as the measured
    baseline and as the differential-test oracle;
  * backend.jax_backend.JaxBackend — batched packed-array kernels on TPU.

Shadow-run numbering follows the reference: simplified graphs live at run
1000+i (preprocessing.go:15), differential graphs at 2000+i
(differential-provenance.go:40).

Determinism note: the reference iterates Go maps in several outputs
(corrections, extensions, prototype collection order), so its output ordering
is nondeterministic (SURVEY.md §7 hard part 5).  This rebuild defines canonical
deterministic orders, documented on each method; parity comparisons against
the reference must compare as sets.
"""

from __future__ import annotations

import abc

from nemo_tpu.ingest.datatypes import MissingEvent
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.report.dot import DotGraph


class NoSuccessfulRunError(RuntimeError):
    """Raised when an analysis that needs a baseline "good" run (differential
    provenance, trigger queries) runs on a corpus where no run succeeded.
    The reference hard-codes run 0 as the good run
    (differential-provenance.go:22-26, corrections.go:210-216) and silently
    produces a nonsense diff when run 0 failed; the rebuild raises instead."""


class GraphBackend(abc.ABC):
    """Interface over the graph analytics engine (reference: main.go:33-44)."""

    #: True when the backend exposes the per-run decomposition hooks below
    #: (proto_tables_by_run / achieved_pre_goal_counts /
    #: extension_suggestions) that the segment-incremental map/reduce
    #: pipeline (analysis/delta.py) merges across store segments.  Backends
    #: without them still run through run_debug, but always as one
    #: monolithic map with partial caching disabled.
    supports_delta = False

    #: True when the backend implements :meth:`synth_candidates` — the
    #: per-run extension-candidate extraction the corpus-ranked repair
    #: synthesis (analysis/synth.py, ISSUE 13) reduces across segments.
    #: Backends without it produce reports with no repairs.json section.
    supports_synth = False

    def stream_clone(self):
        """A fresh backend instance suitable for the segment-streamed map
        (analysis/stream.py): the double-buffered prefetch initializes
        segment k+1's instance on a background thread while segment k's
        dispatches drain, so one shared mutable instance cannot serve both.
        None (the default) disables streaming for this backend; overriders
        should share whatever cross-corpus state is expensive (compiled
        program caches, executors) and return an instance whose
        init_graph_db is safe to call on a non-main thread."""
        return None

    def good_run_iter(self) -> int:
        """Iteration of the baseline successful run used for differential
        provenance and the trigger queries.  The first successful run that
        actually ACHIEVED the consequent — Molly marks vacuous runs (the
        antecedent never held, so the invariant holds trivially) status
        "success" too, and a vacuous baseline would make every diff silently
        near-empty.  Identical to the reference's hard-coded run 0
        (differential-provenance.go:22, corrections.go:210) in the normal
        Molly layout where run 0 is the failure-free execution.  Falls back
        to the first status-success run when no success achieved the
        consequent; raises NoSuccessfulRunError when no run succeeded.
        (Selection logic lives in analysis/delta.py:choose_good_run — ONE
        definition shared with the pipeline-level planner.)"""
        assert self.molly is not None
        from nemo_tpu.analysis.delta import choose_good_run

        good = choose_good_run(self.molly)
        if good is None:
            raise NoSuccessfulRunError(
                "no successful run in this corpus: differential provenance "
                "and correction synthesis need a good run to diff against"
            )
        return good

    # ---- per-run decomposition hooks (the map side of analysis/delta.py):
    # implemented by backends that can slice their cross-run analyses per
    # run, which is what makes segment partials mergeable.

    def proto_tables_by_run(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[dict[int, list[str]], dict[int, set[str]]]:
        """(per success run: ordered qualifying prototype rule tables — []
        when the run did not achieve the antecedent; per failed run: the
        distinct rule tables of its simplified consequent graph).  The
        prototype intersection/union and missing lists are pure set algebra
        over these (analysis/protos.py), computed in the reduce."""
        raise NotImplementedError

    def achieved_pre_goal_counts(self) -> dict[int, int]:
        """Per run: the count of antecedent goals with condition_holds
        (extensions.go:25-50 counts goals, not runs) — summed across
        segments in the reduce to decide all_runs_achieved_pre."""
        raise NotImplementedError

    def extension_suggestions(self) -> list[str]:
        """The extension suggestion list from the baseline run's antecedent
        provenance, UNgated (generate_extensions applies the all-achieved
        gate, which is global — the reduce applies it instead)."""
        raise NotImplementedError

    def synth_candidates(self, iters: list[int]) -> dict[int, list[str]]:
        """Per run in ``iters``: the SORTED distinct extension-candidate
        rule tables of its antecedent provenance — async rules adjacent to
        the condition boundary (analysis/queries.py:extension_candidates,
        extensions.go:63-67), generalized from the baseline-run-only
        reference to every run so the reduce can rank candidates by
        supporting-run count across the corpus (analysis/synth.py).
        Array backends batch the extraction (the ``synth_ext`` kernel
        family); the Python oracle walks one PGraph per run."""
        raise NotImplementedError

    def baseline_run_iter(self) -> int:
        """The good run when one exists, else the first run.  Used where a
        representative provenance graph is enough (extension candidates read
        the antecedent provenance's async boundary, which failed runs have
        too — extensions.go:63-67 uses run 0 unconditionally)."""
        try:
            return self.good_run_iter()
        except NoSuccessfulRunError:
            assert self.molly is not None
            return self.molly.runs[0].iteration

    @abc.abstractmethod
    def init_graph_db(self, conn: str, molly: MollyOutput) -> None:
        """Attach to the backing store and register the runs
        (reference: InitGraphDB, graphing/helpers.go:17-55)."""

    @abc.abstractmethod
    def close_db(self) -> None:
        """Release resources (reference: CloseDB, graphing/helpers.go:58-86)."""

    @abc.abstractmethod
    def load_raw_provenance(self) -> None:
        """Load every run's pre/post provenance and mark condition_holds
        (reference: LoadRawProvenance, graphing/pre-post-prov.go:247-285).

        Condition marking semantics (pre-post-prov.go:220-228): find the root
        goal (table == condition, no incoming edge), its child rules with
        table == condition, and THEIR child goals g; set condition_holds on
        every goal whose table equals the condition or equals any g.table.
        """

    @abc.abstractmethod
    def simplify_prov(self, iters: list[int]) -> None:
        """Create simplified shadow graphs at run 1000+i
        (reference: SimplifyProv, graphing/preprocessing.go:351-387).

        Two passes per (run, condition):
        (a) clean copy (preprocessing.go:17-27): keep nodes/edges on
            Goal-[*0..]->Goal paths — i.e. keep all goals, drop rules lacking
            an incoming or outgoing goal edge, keep edge g->r iff r has an
            outgoing goal, r->g iff r has an incoming goal;
        (b) @next chain contraction (preprocessing.go:66-348): replace each
            connected component (>=2 rules) of the {type=="next" rules +
            goals strictly between two next rules} subgraph by one synthetic
            Rule{type: "collapsed", table: t, label: "t_collapsed", id:
            "run_<1000+i>_<cond>_<t>_collapsed_<k>"}, connecting the goal
            predecessors of the component's head rules and the goal successors
            of its tail rules, then deleting the component.  (The reference
            enumerates variable-length paths greedily longest-first with a
            seen-set, which both under- and over-merges on branching chains
            and is order-dependent; component semantics are its deterministic
            closure and coincide on linear chains — the shape @next chains
            actually take.)
        """

    molly: MollyOutput | None

    def create_hazard_analysis(
        self, fault_inj_out: str, iters: list[int] | None = None
    ) -> list[DotGraph]:
        """Recolored space-time diagram per run
        (reference: CreateHazardAnalysis, graphing/hazard-analysis.go:16-88).
        Purely host-side (reads Molly's DOT files + the holds maps), so it is
        shared by all backends.  `iters` restricts to a subset of runs (the
        pipeline's figure policy); None = all runs, the reference behavior."""
        from nemo_tpu.report.figures import create_hazard_dot

        assert self.molly is not None
        by_iter = {r.iteration: r for r in self.molly.runs}
        run_ids = [r.iteration for r in self.molly.runs] if iters is None else list(iters)
        dots = []
        # Fault-injection runs within a family repeat the same spacetime
        # diagram and holds-maps wholesale; memoize the parse+recolor on the
        # full inputs so 10k runs cost ~tens of parses, not 10k (measured
        # ~4 s/family at stress scale).  Identical inputs SHARE the returned
        # DotGraph object — callers (the report writer / render scheduler)
        # treat figures as frozen after creation.
        memo: dict[tuple, object] = {}
        for i in run_ids:
            run = by_iter[i]
            # Layout-aware read-or-synthesize (ingest/adapters.py seam):
            # Molly ships per-run DOT files; other injectors get the
            # deterministic message-history synthesis.
            text = self.molly.spacetime_dot_text(run.iteration, run=run)
            key = (
                text,
                tuple(sorted(run.time_pre_holds.items())),
                tuple(sorted(run.time_post_holds.items())),
            )
            dot = memo.get(key)
            if dot is None:
                dot = memo[key] = create_hazard_dot(
                    text, run.time_pre_holds, run.time_post_holds
                )
            dots.append(dot)
        return dots

    @abc.abstractmethod
    def create_prototypes(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[list[str], list[list[str]], list[str], list[list[str]]]:
        """Success prototypes over simplified consequent provenance
        (reference: CreatePrototypes, graphing/prototype.go:209-256).

        Returns (inter_proto, inter_proto_missing_per_failed_run, union_proto,
        union_proto_missing_per_failed_run), all entries wrapped in <code>
        for report parity (prototype.go:196,246,250).

        Per achieving run, the rule set is every rule table on a path
        root-[1]->rule-[*1..]->rule from an in-degree-0 goal (prototype.go:12),
        gated on the run having achieved pre (prototype.go:13-15).  Canonical
        per-run order: ascending min rule-depth, then table name.  The
        intersection keeps the first achieving run's order (prototype.go:82);
        the union interleaves runs positionally (prototype.go:114-130).  The
        condition's own table is excluded from both (prototype.go:106,120).
        """

    @abc.abstractmethod
    def pull_pre_post_prov(
        self, iters: list[int] | None = None
    ) -> tuple[list[DotGraph], list[DotGraph], list[DotGraph], list[DotGraph]]:
        """Per-run DOT graphs: (pre, post, pre_clean, post_clean), aligned
        with `iters` (None = all runs, the reference behavior)
        (reference: PullPrePostProv, graphing/pre-post-prov.go:288-459)."""

    @abc.abstractmethod
    def create_naive_diff_prov(
        self,
        symmetric: bool,
        failed_iters: list[int],
        success_post_dot: DotGraph,
        dot_iters: list[int] | None = None,
    ) -> tuple[list[DotGraph], list[DotGraph], list[list[MissingEvent]]]:
        """Differential provenance good-minus-bad per failed run
        (reference: CreateNaiveDiffProv, differential-provenance.go:18-243).

        Diff graph (per failed run f) = nodes/edges on paths g1-[*0..]->g2 of
        run 0's raw consequent provenance whose ENDPOINT goals' labels do not
        occur among run f's consequent goal labels (endpoints only are
        filtered, differential-provenance.go:23-28).  Missing events = for the
        longest root->leaf paths of the diff graph, the terminal rule and all
        its goal children (differential-provenance.go:82-98; the child match
        at :94 has no leaf constraint).  `symmetric` is accepted but unused,
        matching the reference (:18).

        Unlike the reference — whose template-substitution bug diffs every
        failed run after the first against the FIRST failed run's labels
        (differential-provenance.go:43) — each failed run is diffed against
        its own labels.

        Missing events are computed (and returned) for every failed run; the
        overlay DOTs materialize only for runs in `dot_iters` (None = all
        failed runs, the reference behavior) — the pipeline's figure policy
        at stress scale.
        """

    @abc.abstractmethod
    def generate_corrections(self) -> list[str]:
        """Correction suggestions from run 0's trigger boundaries
        (reference: GenerateCorrections, graphing/corrections.go:202-328).
        Output strings are presentation-ready HTML, format-identical to the
        reference; canonical order = aggregation-rule tables sorted, triggers
        in edge order, consequent triggers sorted by (receiver, table)."""

    @abc.abstractmethod
    def generate_extensions(self) -> tuple[bool, list[str]]:
        """(all_runs_achieved_pre, extension suggestions)
        (reference: GenerateExtensions, graphing/extensions.go:13-99).
        Extensions are async rules of run 0's antecedent provenance adjacent
        to the condition boundary, suggested for hardening; canonical order =
        sorted by rule table."""
