"""ServiceBackend: the JaxBackend with its device kernels on the gRPC sidecar.

The north-star two-process architecture (SURVEY.md §7): a thin CLI process
does ingestion, host assembly, and report writing, while the sidecar owns the
accelerator.  This backend is exactly the JaxBackend with the device boundary
swapped — every kernel call (condition marking, simplify, prototypes, diff)
travels the Kernel RPC as a (verb, named arrays, static params) triple and
executes in the sidecar through the same LocalExecutor dispatch table, so the
two deployments are bit-identical by construction (tests/test_service.py).

Select with `--graph-backend=service`; the sidecar address comes from
`-graphDBConn` (the reference's store-connection flag, retargeted) or the
constructor.  Start the sidecar with `python -m nemo_tpu.service.server`.
"""

from __future__ import annotations

from nemo_tpu.backend.jax_backend import JaxBackend
from nemo_tpu.ingest.molly import MollyOutput


class ServiceBackend(JaxBackend):
    def __init__(self, target: str = "127.0.0.1:50051", max_batch: int | None = None) -> None:
        self.target = target
        # The executor (and its channel) is created lazily in init_graph_db —
        # the reference's InitGraphDB is likewise where the store connection
        # opens (graphing/helpers.go:38-49) — so the backend is reusable
        # across corpora after close_db.
        super().__init__(max_batch=max_batch, executor=_Unconnected())
        #: True on a stream_clone sharing the parent's live channel: close_db
        #: then detaches instead of closing (the parent owns the lifetime).
        self._shared_executor = False

    def init_graph_db(self, conn: str, molly: MollyOutput) -> None:
        from nemo_tpu.service.client import RemoteExecutor

        if conn and not conn.startswith("bolt://"):
            self.target = conn
        # Reconnect when unconnected OR re-initialized with a different
        # sidecar address (JaxBackend supports reuse without close_db, so a
        # stale connection here would silently route kernels to the old host).
        if isinstance(self.executor, _Unconnected):
            self.executor = RemoteExecutor(target=self.target)
        elif self.executor.target != self.target:
            self.executor.close()
            self.executor = _Unconnected()
            self.executor = RemoteExecutor(target=self.target)
        super().init_graph_db(conn, molly)

    def stream_clone(self) -> "ServiceBackend":
        """Per-segment clone for the streamed map.  A connected parent's
        executor is SHARED (one gRPC channel + compile-cache affinity
        across all segments) and flagged so the clone's close_db detaches
        without closing it — the parent owns the channel lifetime; closing
        it after segment 1 would kill every later segment's RPCs.  An
        unconnected parent's clone connects lazily in its own
        init_graph_db and owns (and closes) that channel itself."""
        clone = type(self)(target=self.target, max_batch=self.max_batch)
        if not isinstance(self.executor, _Unconnected):
            clone.executor = self.executor
            clone._shared_executor = True
        return clone

    def _resolve_max_batch(self):
        """The sidecar owns the accelerator, so the client's platform says
        nothing about the right dispatch bound: keep single-dispatch on
        auto; an explicit NEMO_MAX_BATCH (shared parser, so the semantics
        cannot diverge from the in-process backend) still bounds the
        dispatches when the operator knows the sidecar is CPU-bound."""
        from nemo_tpu.backend.jax_backend import _NO_OVERRIDE, _max_batch_env

        override = _max_batch_env()
        return None if override is _NO_OVERRIDE else override

    def _resolve_narrow_xfer(self) -> bool:
        """Upload-dtype narrowing for RemoteExecutor clients: ON by default
        (ADVICE r5 #1) — the narrowed planes cross the Kernel RPC and the
        sidecar's own host->device transfer, both bandwidth-priced
        regardless of what jax platform THIS process fell back to.  This
        also keeps the client's dispatch signature aligned with what a
        prewarm running on the (device-owning) sidecar compiles: both
        resolve to the narrow int8/int16 program.  An explicit
        NEMO_NARROW_XFER still wins (shared spelling rules)."""
        from nemo_tpu.backend.jax_backend import _narrow_xfer_env

        override = _narrow_xfer_env()
        return True if override is None else bool(override)

    def _resolve_giant_impl(self) -> str:
        """Giant crossover routing: "auto" keeps the Kernel RPC — the
        sidecar owns the accelerator, so the client's own jax platform is
        the wrong crossover signal.  The RPC'd verb is the DENSE giant
        dispatch for wire compatibility with deployed sidecars; the
        sparse-device giant step (ISSUE 10, the in-process real-device
        default) rides the same Kernel RPC under NEMO_GIANT_IMPL=
        sparse_device or the NEMO_ANALYSIS_IMPL=sparse_device umbrella.
        An explicit NEMO_GIANT_IMPL=host (or the NEMO_ANALYSIS_IMPL=sparse
        umbrella) routes the exact sparse analysis client-side (useful
        when the sidecar itself is known to be CPU-bound)."""
        from nemo_tpu.backend.jax_backend import _analysis_impl_env, _giant_impl_env

        impl = _giant_impl_env()
        if impl == "auto":
            umbrella = _analysis_impl_env()
            if umbrella in ("sparse", "dense", "sparse_device"):
                return {"sparse": "host", "dense": "device"}.get(umbrella, umbrella)
            return "device"
        return impl

    def _resolve_analysis_impl(self) -> str:
        """Batched-analysis route for RemoteExecutor clients: "auto" keeps
        the dense Kernel RPC — the sidecar owns the accelerator, so the
        client's own jax platform (often a CPU fallback) is the wrong
        routing signal, exactly the narrowing/giant precedents (ADVICE r5
        #1, VERDICT r4 task 2).  An explicit NEMO_ANALYSIS_IMPL=sparse
        still routes every bucket through the client-side CSR host engine
        (serving a sidecar-less degraded mode, or a sidecar known to be
        CPU-bound where the RPC+dispatch costs more than the host
        scatters)."""
        from nemo_tpu.backend.jax_backend import _analysis_impl_env

        impl = _analysis_impl_env()
        return "dense" if impl == "auto" else impl

    def _resolve_synth_impl(self) -> str:
        """Synthesis route for RemoteExecutor clients: "auto" runs the
        bincount host twin CLIENT-side — the synth kernel is a handful of
        single-step scatters whose host cost is far below one Kernel-RPC
        round trip, and a deployed sidecar one release behind has no
        ``synth_ext`` verb to serve (the sparse_diff wire-compat
        precedent).  An explicit NEMO_SYNTH_IMPL=sparse_device still
        ships the verb over the Kernel RPC (a sidecar of this release
        serves it through the same LocalExecutor table), and =python
        keeps the per-run oracle."""
        from nemo_tpu.analysis.synth import synth_impl_env

        impl = synth_impl_env()
        return "sparse" if impl == "auto" else impl

    def close_db(self) -> None:
        super().close_db()
        if isinstance(self.executor, _Unconnected):
            return
        if getattr(self, "_shared_executor", False):
            # Segment clone over the parent's channel (stream_clone):
            # detach without closing — the parent owns the lifetime.
            self.executor = _Unconnected()
            return
        self.executor.close()
        self.executor = _Unconnected()


class _Unconnected:
    """Placeholder executor before init_graph_db / after close_db."""

    def run(self, verb, arrays, params, rows=None):
        raise RuntimeError("ServiceBackend is not connected; call init_graph_db first")

