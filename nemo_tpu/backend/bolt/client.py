"""Bolt v1 connection: handshake, chunked message framing, request/response.

Speaks the public Bolt v1 protocol (what Neo4j 3.3 serves on port 7687;
the reference's vendored Go driver implements the same wire format —
conn.go:35-60 is the `Conn` interface whose Prepare/Query/Exec surface
`BoltConnection.run` replaces).  The backend opens two connections, matching
the reference's Conn1/Conn2 pair (graphing/helpers.go:38-49).

Wire format summary (public spec):
  handshake:  C->S  60:60:B0:17 + four big-endian uint32 version proposals
              S->C  one uint32: the agreed version (0 = refused)
  messages:   PackStream structures, split into chunks; each chunk is a
              2-byte big-endian size header + payload; a zero-size chunk
              terminates the message.
  requests:   INIT 0x01, RUN 0x10, PULL_ALL 0x3F, DISCARD_ALL 0x2F,
              RESET 0x0F, ACK_FAILURE 0x0E
  responses:  SUCCESS 0x70, RECORD 0x71, IGNORED 0x7E, FAILURE 0x7F
"""

from __future__ import annotations

import socket
import struct
from typing import Any
from urllib.parse import unquote, urlparse

from nemo_tpu.backend.bolt.packstream import Structure, pack, unpack_all

BOLT_MAGIC = b"\x60\x60\xb0\x17"
BOLT_VERSION = 1

MSG_INIT = 0x01
MSG_ACK_FAILURE = 0x0E
MSG_RESET = 0x0F
MSG_RUN = 0x10
MSG_DISCARD_ALL = 0x2F
MSG_PULL_ALL = 0x3F
MSG_SUCCESS = 0x70
MSG_RECORD = 0x71
MSG_IGNORED = 0x7E
MSG_FAILURE = 0x7F

MAX_CHUNK = 0xFFFF
DEFAULT_USER_AGENT = "nemo-tpu/bolt-python"


class BoltError(RuntimeError):
    """Server FAILURE response or protocol violation."""

    def __init__(self, code: str, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


class BoltConnection:
    """One Bolt session over TCP.  Not thread-safe; open one per logical
    connection (the reference needs two, graphing/helpers.go:38-49)."""

    def __init__(
        self,
        uri: str = "bolt://127.0.0.1:7687",
        auth: tuple[str, str] | None = None,
        timeout: float = 600.0,
        user_agent: str = DEFAULT_USER_AGENT,
    ) -> None:
        parsed = urlparse(uri)
        if parsed.scheme not in ("bolt", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r} (expected bolt://)")
        host = parsed.hostname or "127.0.0.1"
        port = parsed.port or 7687
        if auth is None and parsed.username:
            # urlparse leaves userinfo percent-encoded; decode so passwords
            # with special characters (p%40ss -> p@ss) authenticate.
            auth = (unquote(parsed.username), unquote(parsed.password or ""))
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        try:
            self._handshake()
            self._init(user_agent, auth)
        except BaseException:
            self._sock.close()
            raise

    # ------------------------------------------------------------- lifecycle

    def _handshake(self) -> None:
        proposals = struct.pack(">IIII", BOLT_VERSION, 0, 0, 0)
        self._sock.sendall(BOLT_MAGIC + proposals)
        agreed = struct.unpack(">I", self._recv_exact(4))[0]
        if agreed != BOLT_VERSION:
            raise BoltError(
                "ProtocolError", f"server refused Bolt v{BOLT_VERSION} (answered {agreed})"
            )

    def _init(self, user_agent: str, auth: tuple[str, str] | None) -> None:
        token: dict[str, Any] = {"scheme": "none"}
        if auth is not None:
            token = {"scheme": "basic", "principal": auth[0], "credentials": auth[1]}
        self._send_message(Structure(MSG_INIT, [user_agent, token]))
        self._expect_success()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "BoltConnection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- messaging

    def run(
        self, statement: str, params: dict[str, Any] | None = None
    ) -> tuple[list[str], list[list[Any]]]:
        """Execute one statement, pull all records.
        Returns (field_names, records)."""
        self._send_message(Structure(MSG_RUN, [statement, params or {}]))
        self._send_message(Structure(MSG_PULL_ALL, []))
        head = self._recv_message()
        if head.signature == MSG_FAILURE:
            # Server enters FAILED state: the pipelined PULL_ALL comes back
            # IGNORED; consume it before recovering with ACK_FAILURE.
            self._recv_message()
            self._ack_failure()
            meta = head.fields[0] if head.fields else {}
            raise BoltError(meta.get("code", "Unknown"), meta.get("message", ""))
        if head.signature != MSG_SUCCESS:
            raise BoltError("ProtocolError", f"unexpected signature 0x{head.signature:02X}")
        fields = (head.fields[0] if head.fields else {}).get("fields", [])
        records: list[list[Any]] = []
        while True:
            msg = self._recv_message()
            if msg.signature == MSG_RECORD:
                records.append(msg.fields[0])
            elif msg.signature == MSG_SUCCESS:
                return fields, records
            elif msg.signature == MSG_FAILURE:
                self._ack_failure()
                meta = msg.fields[0] if msg.fields else {}
                raise BoltError(meta.get("code", "Unknown"), meta.get("message", ""))
            elif msg.signature == MSG_IGNORED:
                raise BoltError("Ignored", "statement ignored (connection in failed state)")
            else:
                raise BoltError("ProtocolError", f"unexpected signature 0x{msg.signature:02X}")

    def exec(self, statement: str, params: dict[str, Any] | None = None) -> list[list[Any]]:
        """run() returning just the records."""
        return self.run(statement, params)[1]

    def reset(self) -> None:
        self._send_message(Structure(MSG_RESET, []))
        self._expect_success()

    # -------------------------------------------------------------- framing

    def _send_message(self, msg: Structure) -> None:
        payload = pack(msg)
        out = bytearray()
        for ofs in range(0, len(payload), MAX_CHUNK):
            chunk = payload[ofs : ofs + MAX_CHUNK]
            out += struct.pack(">H", len(chunk))
            out += chunk
        out += b"\x00\x00"
        self._sock.sendall(bytes(out))

    def _recv_message(self) -> Structure:
        payload = bytearray()
        while True:
            size = struct.unpack(">H", self._recv_exact(2))[0]
            if size == 0:
                if payload:
                    break
                continue  # NOOP chunk (keep-alive)
            payload += self._recv_exact(size)
        msg = unpack_all(bytes(payload))
        if not isinstance(msg, Structure):
            raise BoltError("ProtocolError", f"non-structure message: {type(msg).__name__}")
        return msg

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            data = self._sock.recv(65536)
            if not data:
                raise BoltError("ConnectionError", "server closed the connection")
            self._buf += data
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _expect_success(self) -> dict[str, Any]:
        msg = self._recv_message()
        if msg.signature == MSG_SUCCESS:
            return msg.fields[0] if msg.fields else {}
        if msg.signature == MSG_FAILURE:
            self._ack_failure()
            meta = msg.fields[0] if msg.fields else {}
            raise BoltError(meta.get("code", "Unknown"), meta.get("message", ""))
        raise BoltError("ProtocolError", f"unexpected signature 0x{msg.signature:02X}")

    def _ack_failure(self) -> None:
        try:
            self._send_message(Structure(MSG_ACK_FAILURE, []))
            msg = self._recv_message()
            if msg.signature not in (MSG_SUCCESS, MSG_IGNORED):
                raise BoltError("ProtocolError", "bad ACK_FAILURE response")
        except OSError:
            pass
