"""Pure-Python Bolt v1 driver (the reference vendors a Go equivalent,
vendor/github.com/johnnadratowski/golang-neo4j-bolt-driver, ~3.8k LoC —
conn.go:35-60 is the interface our client mirrors)."""

from nemo_tpu.backend.bolt.client import BoltConnection, BoltError
from nemo_tpu.backend.bolt.packstream import Node, Path, Relationship, pack, unpack

__all__ = [
    "BoltConnection",
    "BoltError",
    "Node",
    "Relationship",
    "Path",
    "pack",
    "unpack",
]
