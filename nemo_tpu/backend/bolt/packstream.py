"""PackStream v1 codec — the wire serialization of the Bolt protocol.

Implements the public PackStream specification (the reference vendors a Go
implementation at vendor/.../golang-neo4j-bolt-driver/encoding/): nulls,
booleans, 64-bit ints (tiny/8/16/32/64), float64, UTF-8 strings, lists, maps,
and structures, plus the graph structure types the analysis code consumes —
Node (signature 0x4E), Relationship (0x52), Path (0x50) — mirroring the
vendored driver's structures/graph types (node.go:9, relationship.go:9,
path.go:9) that the reference type-asserts against (e.g.
graphing/differential-provenance.go:119).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any

SIG_NODE = 0x4E
SIG_RELATIONSHIP = 0x52
SIG_UNBOUND_RELATIONSHIP = 0x72
SIG_PATH = 0x50


@dataclass
class Structure:
    """Generic PackStream structure: signature byte + field list."""

    signature: int
    fields: list[Any] = field(default_factory=list)


@dataclass
class Node:
    identity: int
    labels: list[str]
    properties: dict[str, Any]


@dataclass
class Relationship:
    identity: int
    start: int
    end: int
    type: str
    properties: dict[str, Any]


@dataclass
class UnboundRelationship:
    identity: int
    type: str
    properties: dict[str, Any]


@dataclass
class Path:
    nodes: list[Node]
    relationships: list[UnboundRelationship]
    sequence: list[int]


def pack(value: Any) -> bytes:
    out = bytearray()
    _pack_into(out, value)
    return bytes(out)


def _pack_into(out: bytearray, v: Any) -> None:
    if v is None:
        out.append(0xC0)
    elif v is True:
        out.append(0xC3)
    elif v is False:
        out.append(0xC2)
    elif isinstance(v, int):
        _pack_int(out, v)
    elif isinstance(v, float):
        out.append(0xC1)
        out += struct.pack(">d", v)
    elif isinstance(v, str):
        data = v.encode("utf-8")
        _pack_header(out, len(data), 0x80, (0xD0, 0xD1, 0xD2))
        out += data
    elif isinstance(v, bytes):
        n = len(v)
        if n < 0x100:
            out += bytes((0xCC, n))
        elif n < 0x10000:
            out.append(0xCD)
            out += struct.pack(">H", n)
        else:
            out.append(0xCE)
            out += struct.pack(">I", n)
        out += v
    elif isinstance(v, (list, tuple)):
        _pack_header(out, len(v), 0x90, (0xD4, 0xD5, 0xD6))
        for item in v:
            _pack_into(out, item)
    elif isinstance(v, dict):
        _pack_header(out, len(v), 0xA0, (0xD8, 0xD9, 0xDA))
        for k, item in v.items():
            _pack_into(out, k)
            _pack_into(out, item)
    elif isinstance(v, Structure):
        _pack_struct_header(out, len(v.fields), v.signature)
        for f in v.fields:
            _pack_into(out, f)
    else:
        raise TypeError(f"cannot pack value of type {type(v).__name__}")


def _pack_int(out: bytearray, v: int) -> None:
    if -16 <= v < 128:
        out += struct.pack(">b", v)
    elif -0x80 <= v < 0x80:
        out.append(0xC8)
        out += struct.pack(">b", v)
    elif -0x8000 <= v < 0x8000:
        out.append(0xC9)
        out += struct.pack(">h", v)
    elif -0x80000000 <= v < 0x80000000:
        out.append(0xCA)
        out += struct.pack(">i", v)
    else:
        out.append(0xCB)
        out += struct.pack(">q", v)


def _pack_header(out: bytearray, n: int, tiny_base: int, markers: tuple[int, int, int]) -> None:
    if n < 0x10:
        out.append(tiny_base + n)
    elif n < 0x100:
        out.append(markers[0])
        out.append(n)
    elif n < 0x10000:
        out.append(markers[1])
        out += struct.pack(">H", n)
    else:
        out.append(markers[2])
        out += struct.pack(">I", n)


def _pack_struct_header(out: bytearray, n: int, signature: int) -> None:
    if n < 0x10:
        out.append(0xB0 + n)
    elif n < 0x100:
        out += bytes((0xDC, n))
    else:
        out.append(0xDD)
        out += struct.pack(">H", n)
    out.append(signature)


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("packstream: truncated data")
        b = self.data[self.pos : self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]


def unpack(data: bytes) -> Any:
    r = _Reader(data)
    v = _unpack(r)
    return v


def unpack_all(data: bytes) -> Any:
    """Unpack one value and require full consumption."""
    r = _Reader(data)
    v = _unpack(r)
    if r.pos != len(data):
        raise ValueError(f"packstream: {len(data) - r.pos} trailing bytes")
    return v


def _unpack(r: _Reader) -> Any:
    m = r.u8()
    if m <= 0x7F:  # tiny positive int
        return m
    if m >= 0xF0:  # tiny negative int
        return m - 0x100
    if 0x80 <= m <= 0x8F:
        return r.take(m - 0x80).decode("utf-8")
    if 0x90 <= m <= 0x9F:
        return [_unpack(r) for _ in range(m - 0x90)]
    if 0xA0 <= m <= 0xAF:
        return {_unpack(r): _unpack(r) for _ in range(m - 0xA0)}
    if 0xB0 <= m <= 0xBF:
        return _unpack_struct(r, m - 0xB0)
    if m == 0xC0:
        return None
    if m == 0xC1:
        return struct.unpack(">d", r.take(8))[0]
    if m == 0xC2:
        return False
    if m == 0xC3:
        return True
    if m == 0xC8:
        return struct.unpack(">b", r.take(1))[0]
    if m == 0xC9:
        return struct.unpack(">h", r.take(2))[0]
    if m == 0xCA:
        return struct.unpack(">i", r.take(4))[0]
    if m == 0xCB:
        return struct.unpack(">q", r.take(8))[0]
    if m == 0xCC:
        return bytes(r.take(r.u8()))
    if m == 0xCD:
        return bytes(r.take(struct.unpack(">H", r.take(2))[0]))
    if m == 0xCE:
        return bytes(r.take(struct.unpack(">I", r.take(4))[0]))
    if m == 0xD0:
        return r.take(r.u8()).decode("utf-8")
    if m == 0xD1:
        return r.take(struct.unpack(">H", r.take(2))[0]).decode("utf-8")
    if m == 0xD2:
        return r.take(struct.unpack(">I", r.take(4))[0]).decode("utf-8")
    if m == 0xD4:
        return [_unpack(r) for _ in range(r.u8())]
    if m == 0xD5:
        return [_unpack(r) for _ in range(struct.unpack(">H", r.take(2))[0])]
    if m == 0xD6:
        return [_unpack(r) for _ in range(struct.unpack(">I", r.take(4))[0])]
    if m == 0xD8:
        return {_unpack(r): _unpack(r) for _ in range(r.u8())}
    if m == 0xD9:
        return {_unpack(r): _unpack(r) for _ in range(struct.unpack(">H", r.take(2))[0])}
    if m == 0xDA:
        return {_unpack(r): _unpack(r) for _ in range(struct.unpack(">I", r.take(4))[0])}
    if m == 0xDC:
        return _unpack_struct(r, r.u8())
    if m == 0xDD:
        return _unpack_struct(r, struct.unpack(">H", r.take(2))[0])
    raise ValueError(f"packstream: unknown marker 0x{m:02X}")


def _unpack_struct(r: _Reader, size: int) -> Any:
    sig = r.u8()
    fields = [_unpack(r) for _ in range(size)]
    if sig == SIG_NODE:
        return Node(identity=fields[0], labels=fields[1], properties=fields[2])
    if sig == SIG_RELATIONSHIP:
        return Relationship(
            identity=fields[0], start=fields[1], end=fields[2], type=fields[3], properties=fields[4]
        )
    if sig == SIG_UNBOUND_RELATIONSHIP:
        return UnboundRelationship(identity=fields[0], type=fields[1], properties=fields[2])
    if sig == SIG_PATH:
        return Path(nodes=fields[0], relationships=fields[1], sequence=fields[2])
    return Structure(signature=sig, fields=fields)
