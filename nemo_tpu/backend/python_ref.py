"""PythonBackend: in-process property-graph oracle.

Implements every GraphBackend verb with direct graph traversals that mirror
the reference's Cypher semantics (see base.py docstrings for the per-verb
spec and reference citations).  This backend plays two roles:

  * the measured baseline the JAX/TPU backend must beat (the reference's
    Neo4j container is not runnable here; this is the same sequential
    one-run-at-a-time execution model without the network round-trips, i.e.
    a strictly stronger baseline than Neo4j per SURVEY.md §6's cost model);
  * the differential-test oracle: tests assert the JAX kernels reproduce
    these results exactly.
"""

from __future__ import annotations

import dataclasses

from nemo_tpu.analysis.corrections import (
    PostTrigger,
    PreTrigger,
    synthesize_corrections,
    synthesize_extensions,
)
from nemo_tpu.analysis.protos import intersect_proto, missing_from, union_proto, wrap_code
from nemo_tpu.analysis.queries import (
    extension_candidates,
    find_post_triggers,
    find_pre_triggers,
)
from nemo_tpu.graphs.pgraph import PGraph, PNode, build_pgraph
from nemo_tpu.ingest.datatypes import Goal, MissingEvent, Rule
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.report.dot import DotGraph
from nemo_tpu.report.figures import create_diff_dot, create_dot

from .base import GraphBackend

CLEAN_OFFSET = 1000  # shadow run offset for simplified graphs (preprocessing.go:15)
DIFF_OFFSET = 2000  # shadow run offset for diff graphs (differential-provenance.go:40)


class PythonBackend(GraphBackend):
    #: Per-run decomposition hooks implemented (proto_tables_by_run /
    #: achieved_pre_goal_counts / extension_suggestions), so the oracle
    #: exercises the same map/reduce pipeline split as the array backends
    #: (analysis/delta.py) — the reduce's set algebra is differential-tested
    #: against create_prototypes through the byte-parity suites.
    supports_delta = True
    #: Per-run synthesis candidates implemented (the per-run PGraph walk —
    #: THE parity oracle of the batched synth kernels, ISSUE 13).
    supports_synth = True

    def __init__(self) -> None:
        self.molly: MollyOutput | None = None
        # (run_id, condition) -> graph; shadow runs use offset run ids.
        self.graphs: dict[tuple[int, str], PGraph] = {}

    # ------------------------------------------------------------------ setup

    def init_graph_db(self, conn: str, molly: MollyOutput) -> None:
        # No external store: conn is accepted for CLI parity and ignored.
        self.molly = molly
        self.graphs = {}

    def close_db(self) -> None:
        self.graphs = {}

    # ------------------------------------------------------------------- load

    def load_raw_provenance(self) -> None:
        assert self.molly is not None
        for run in self.molly.runs:
            for cond, prov in (("pre", run.pre_prov), ("post", run.post_prov)):
                g = build_pgraph(prov)
                self._mark_condition_holds(g, cond)
                self.graphs[(run.iteration, cond)] = g

    @staticmethod
    def _mark_condition_holds(g: PGraph, condition: str) -> None:
        """Reference: graphing/pre-post-prov.go:218-244 (see base.py)."""
        trigger_tables: set[str] = set()
        for root in g.roots():
            if not root.is_goal or root.table != condition:
                continue
            for rule_id in g.out[root.id]:
                rule = g.nodes[rule_id]
                if rule.is_goal or rule.table != condition:
                    continue
                for goal_id in g.out[rule_id]:
                    child = g.nodes[goal_id]
                    if child.is_goal:
                        trigger_tables.add(child.table)
        if trigger_tables:
            for node in g.nodes.values():
                if node.is_goal and (node.table == condition or node.table in trigger_tables):
                    node.cond_holds = True

    # --------------------------------------------------------------- simplify

    def simplify_prov(self, iters: list[int]) -> None:
        for i in iters:
            for cond in ("pre", "post"):
                clean = self._clean_copy(self.graphs[(i, cond)], i, cond)
                self._collapse_next_chains(clean, i, cond)
                self.graphs[(CLEAN_OFFSET + i, cond)] = clean

    @staticmethod
    def _clean_copy(
        g: PGraph, iteration: int, cond: str, kept_rule_ids: set[str] | None = None
    ) -> PGraph:
        """Goal-[*0..]->Goal path restriction (preprocessing.go:17-27; see
        base.py for the degree-mask formulation).  Node IDs are rewritten from
        run_<i>_ to run_<1000+i>_ exactly as the reference's sed pass does
        (preprocessing.go:33-54).  `kept_rule_ids` lets a backend supply the
        kept-rule selection from its own store (Neo4jBackend's Cypher degree
        query) instead of the local degree check."""
        old_prefix = f"run_{iteration}_"
        new_prefix = f"run_{CLEAN_OFFSET + iteration}_"

        def rename(nid: str) -> str:
            return new_prefix + nid[len(old_prefix):] if nid.startswith(old_prefix) else nid

        out = PGraph()
        keep: set[str] = set()
        for node in g.nodes.values():
            if node.is_goal:
                keep.add(node.id)
            elif kept_rule_ids is not None:
                if node.id in kept_rule_ids:
                    keep.add(node.id)
            else:
                has_in = bool(g.inn[node.id])
                has_out = bool(g.out[node.id])
                if has_in and has_out:
                    keep.add(node.id)
        for nid in g.nodes:  # original insertion order (deterministic)
            if nid in keep:
                out.add_node(dataclasses.replace(g.nodes[nid], id=rename(nid)))
        for src, dst in g.edge_order:
            if src in keep and dst in keep:
                out.add_edge(rename(src), rename(dst))
        return out

    @staticmethod
    def _collapse_next_chains(g: PGraph, iteration: int, cond: str) -> None:
        """@next chain contraction (preprocessing.go:66-348; deterministic
        component semantics per base.py docstring), applied in place."""
        run = CLEAN_OFFSET + iteration
        next_rules = {n.id for n in g.nodes.values() if not n.is_goal and n.type == "next"}
        chain_goals = {
            n.id
            for n in g.nodes.values()
            if n.is_goal
            and any(p in next_rules for p in g.inn[n.id])
            and any(s in next_rules for s in g.out[n.id])
        }
        members = next_rules | chain_goals
        if not members:
            return

        # Weakly-connected components of the induced subgraph, discovered in
        # node insertion order for determinism.
        comp_of: dict[str, int] = {}
        components: list[list[str]] = []
        for start in g.nodes:
            if start not in members or start in comp_of:
                continue
            comp = []
            stack = [start]
            comp_of[start] = len(components)
            while stack:
                v = stack.pop()
                comp.append(v)
                for w in list(g.out[v]) + list(g.inn[v]):
                    if w in members and w not in comp_of:
                        comp_of[w] = len(components)
                        stack.append(w)
            components.append(comp)

        # Deterministic component order: by the insertion index of each
        # component's first head rule (matches the packed-array kernel, which
        # numbers collapsed rules by representative slot order).
        node_index = {nid: i for i, nid in enumerate(g.nodes)}
        ordered: list[tuple[int, list[str], list[str], list[str]]] = []
        for comp in components:
            comp_set = set(comp)
            comp_rules = [v for v in comp if v in next_rules]
            if len(comp_rules) < 2:
                continue  # a path needs two next rules (preprocessing.go:71)

            # Head rules: no predecessor chain goal within the component;
            # tail rules: no successor chain goal within the component.
            heads = sorted(
                (r for r in comp_rules if not any(p in comp_set for p in g.inn[r])),
                key=lambda r: node_index[r],
            )
            tails = [r for r in comp_rules if not any(s in comp_set for s in g.out[r])]
            rep_index = node_index[(heads or sorted(comp_rules, key=lambda r: node_index[r]))[0]]
            ordered.append((rep_index, comp, heads, tails))

        k = 0
        for _, comp, heads, tails in sorted(ordered):
            comp_set = set(comp)
            comp_rules = [v for v in comp if v in next_rules]
            # Preds/succs outside the component (preprocessing.go:146-245).
            preds: list[str] = []
            for r in heads:
                preds.extend(p for p in g.inn[r] if p not in comp_set and g.nodes[p].is_goal)
            succs: list[str] = []
            for r in tails:
                succs.extend(s for s in g.out[r] if s not in comp_set and g.nodes[s].is_goal)

            table = g.nodes[(heads or comp_rules)[0]].table
            label = f"{table}_collapsed"
            # ID format per preprocessing.go:252.
            coll_id = f"run_{run}_{cond}_{label}_{k}"
            k += 1
            g.add_node(
                PNode(id=coll_id, is_goal=False, label=label, table=table, type="collapsed")
            )
            for p in dict.fromkeys(preds):
                g.add_edge(p, coll_id)
            for s in dict.fromkeys(succs):
                g.add_edge(coll_id, s)
            for v in comp:
                g.remove_node(v)

    # (create_hazard_analysis is inherited from GraphBackend — host-side only.)

    # ------------------------------------------------------------- prototypes

    def _achieved_pre(self, iteration: int) -> bool:
        """Any goal in the run's simplified antecedent graph with
        condition_holds (prototype.go:13-15, queried at run 1000+i)."""
        g = self.graphs[(CLEAN_OFFSET + iteration, "pre")]
        return any(n.cond_holds for n in g.goals())

    def proto_rule_tables(self, iteration: int, condition: str) -> list[str]:
        """Ordered rule tables on root-[1]->rule-[*1..]->rule paths of the
        simplified graph (prototype.go:11-24), gated on achieving pre.
        Canonical order: (min rule-depth, table)."""
        if not self._achieved_pre(iteration):
            return []
        g = self.graphs[(CLEAN_OFFSET + iteration, condition)]
        root_ids = [n.id for n in g.roots() if n.is_goal]
        if not root_ids:
            return []
        reach = set()
        for rid in root_ids:
            reach |= g.descendants(rid)
        qualifying: dict[str, int] = {}  # table -> min rule-depth
        # Rule-depth: number of rules on the shortest root path (BFS by hops).
        depth: dict[str, int] = {}
        frontier = list(root_ids)
        hops = 0
        seen = set(root_ids)
        while frontier:
            nxt = []
            for v in frontier:
                for w in g.out[v]:
                    if w not in seen:
                        seen.add(w)
                        depth[w] = hops + 1
                        nxt.append(w)
            frontier = nxt
            hops += 1
        for rid in reach:
            node = g.nodes[rid]
            if node.is_goal:
                continue
            has_rule_descendant = any(not g.nodes[d].is_goal for d in g.descendants(rid))
            has_rule_ancestor = any(
                not g.nodes[a].is_goal for a in g.coreachable_to([rid]) if a != rid and a in reach
            )
            if has_rule_descendant or has_rule_ancestor:
                rule_depth = (depth.get(rid, 0) + 1) // 2  # hops alternate goal/rule
                prev = qualifying.get(node.table)
                if prev is None or rule_depth < prev:
                    qualifying[node.table] = rule_depth
        return [t for t, _ in sorted(qualifying.items(), key=lambda kv: (kv[1], kv[0]))]

    def clean_rule_tables(self, iteration: int, condition: str) -> set[str]:
        """All distinct rule tables of the simplified graph (prototype.go:143-147)."""
        g = self.graphs[(CLEAN_OFFSET + iteration, condition)]
        return {n.table for n in g.rules()}

    def create_prototypes(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[list[str], list[list[str]], list[str], list[list[str]]]:
        per_run = [self.proto_rule_tables(i, "post") for i in success_iters]
        inter = intersect_proto(per_run, "post")
        union = union_proto(per_run, "post")
        inter_miss = []
        union_miss = []
        for f in failed_iters:
            present = self.clean_rule_tables(f, "post")
            inter_miss.append(missing_from(inter, present))
            union_miss.append(missing_from(union, present))
        return wrap_code(inter), inter_miss, wrap_code(union), union_miss

    def proto_tables_by_run(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[dict[int, list[str]], dict[int, set[str]]]:
        return (
            {i: self.proto_rule_tables(i, "post") for i in success_iters},
            {f: self.clean_rule_tables(f, "post") for f in failed_iters},
        )

    # ------------------------------------------------------------------- pull

    def pull_pre_post_prov(
        self, iters: list[int] | None = None
    ) -> tuple[list[DotGraph], list[DotGraph], list[DotGraph], list[DotGraph]]:
        assert self.molly is not None
        run_ids = [r.iteration for r in self.molly.runs] if iters is None else list(iters)
        pre, post, pre_clean, post_clean = [], [], [], []
        for i in run_ids:
            pre.append(create_dot(self.graphs[(i, "pre")], "pre"))
            post.append(create_dot(self.graphs[(i, "post")], "post"))
            pre_clean.append(create_dot(self.graphs[(CLEAN_OFFSET + i, "pre")], "pre"))
            post_clean.append(create_dot(self.graphs[(CLEAN_OFFSET + i, "post")], "post"))
        return pre, post, pre_clean, post_clean

    # ------------------------------------------------------------------- diff

    def diff_graph(self, failed_iter: int) -> PGraph:
        """Good-minus-bad subgraph for one failed run (see base.py spec)."""
        g = self.good_run_iter()
        good = self.graphs[(g, "post")]
        bad = self.graphs[(failed_iter, "post")]
        fail_labels = {n.label for n in bad.goals()}
        ok_goals = [n.id for n in good.goals() if n.label not in fail_labels]
        fwd = good.reachable_from(ok_goals)  # >=0 hops from an ok goal
        bwd = good.coreachable_to(ok_goals)  # >=0 hops to an ok goal

        old_prefix = f"run_{g}_"
        new_prefix = f"run_{DIFF_OFFSET + failed_iter}_"

        def rename(nid: str) -> str:
            return new_prefix + nid[len(old_prefix):] if nid.startswith(old_prefix) else nid

        out = PGraph()
        for nid in good.nodes:
            if nid in fwd and nid in bwd:
                out.add_node(dataclasses.replace(good.nodes[nid], id=rename(nid)))
        for src, dst in good.edge_order:
            # Edge lies on an ok-goal->ok-goal path iff its source is
            # forward-reachable and its target backward-reachable; that also
            # implies both endpoints are in the node set.
            if src in fwd and dst in bwd:
                out.add_edge(rename(src), rename(dst))
        return out

    @staticmethod
    def _diff_missing(diff: PGraph) -> list[MissingEvent]:
        """Frontier of the diff graph: rules under the longest root->leaf
        paths plus all their goal children (differential-provenance.go:82-98)."""
        roots = [n.id for n in diff.roots() if n.is_goal]
        # Longest path DP over the DAG from roots.
        order: list[str] = []
        indeg = {nid: len(diff.inn[nid]) for nid in diff.nodes}
        stack = [nid for nid, d in indeg.items() if d == 0]
        while stack:
            v = stack.pop()
            order.append(v)
            for w in diff.out[v]:
                indeg[w] -= 1
                if indeg[w] == 0:
                    stack.append(w)
        dist = {nid: (0 if nid in roots else -(10**9)) for nid in diff.nodes}
        for v in order:
            for w in diff.out[v]:
                if dist[v] + 1 > dist[w]:
                    dist[w] = dist[v] + 1
        best = -1
        frontier_rules: dict[int, list[str]] = {}
        for nid, node in diff.nodes.items():
            if node.is_goal or not diff.out[nid]:
                continue
            for child in diff.out[nid]:
                cnode = diff.nodes[child]
                # The rule must itself lie on the maximal path: its own longest
                # root distance plus the final hop equals the leaf's distance
                # (length(path) = maxLen, differential-provenance.go:89-91).
                if (
                    cnode.is_goal
                    and not diff.out[child]
                    and dist[child] >= 1
                    and dist[nid] + 1 == dist[child]
                ):
                    frontier_rules.setdefault(dist[child], [])
                    if nid not in frontier_rules[dist[child]]:
                        frontier_rules[dist[child]].append(nid)
                    best = max(best, dist[child])
        if best < 0:
            return []
        missing = []
        for rid in sorted(frontier_rules[best]):
            rule = diff.nodes[rid]
            goals = sorted(
                (
                    diff.nodes[c]
                    for c in diff.out[rid]
                    if diff.nodes[c].is_goal  # all goal children, not only leaves (:94)
                ),
                key=lambda n: n.id,
            )
            missing.append(
                MissingEvent(
                    rule=Rule(id=rule.id, label=rule.label, table=rule.table, type=rule.type),
                    goals=[
                        Goal(
                            id=c.id,
                            label=c.label,
                            table=c.table,
                            time=c.time,
                            cond_holds=c.cond_holds,
                        )
                        for c in goals
                    ],
                )
            )
        return missing

    def create_naive_diff_prov(
        self,
        symmetric: bool,
        failed_iters: list[int],
        success_post_dot: DotGraph,
        dot_iters: list[int] | None = None,
    ) -> tuple[list[DotGraph], list[DotGraph], list[list[MissingEvent]]]:
        if not failed_iters:
            return [], [], []
        dot_set = set(failed_iters if dot_iters is None else dot_iters)
        diff_dots, failed_dots, missing_events = [], [], []
        good_iter = self.good_run_iter()
        for f in failed_iters:
            diff = self.diff_graph(f)
            self.graphs[(DIFF_OFFSET + f, "post")] = diff
            missing = self._diff_missing(diff)
            missing_events.append(missing)
            if f not in dot_set:
                continue
            diff_dot, failed_dot = create_diff_dot(
                DIFF_OFFSET + f, diff, self.graphs[(f, "post")], good_iter, success_post_dot, missing
            )
            diff_dots.append(diff_dot)
            failed_dots.append(failed_dot)
        return diff_dots, failed_dots, missing_events

    # ------------------------------------------------------------ corrections

    def find_pre_triggers(self, run: int) -> list[PreTrigger]:
        return find_pre_triggers(self.graphs[(run, "pre")])

    def find_post_triggers(self, run: int) -> list[PostTrigger]:
        return find_post_triggers(self.graphs[(run, "post")])

    def generate_corrections(self) -> list[str]:
        g = self.good_run_iter()
        return synthesize_corrections(self.find_pre_triggers(g), self.find_post_triggers(g))

    # ------------------------------------------------------------- extensions

    def achieved_pre_goal_counts(self) -> dict[int, int]:
        assert self.molly is not None
        # Count goals with table == "pre" and condition_holds per raw
        # antecedent graph (extensions.go:25-50 counts goals, not runs).
        return {
            run.iteration: sum(
                1
                for n in self.graphs[(run.iteration, "pre")].goals()
                if n.table == "pre" and n.cond_holds
            )
            for run in self.molly.runs
        }

    def extension_suggestions(self) -> list[str]:
        candidates = extension_candidates(self.graphs[(self.baseline_run_iter(), "pre")])
        return synthesize_extensions(candidates)

    def synth_candidates(self, iters: list[int]) -> dict[int, list[str]]:
        # The per-run oracle (ISSUE 13): one PGraph walk per run — exactly
        # what the batched synth_ext kernels must reproduce per row.
        return {
            i: sorted(set(extension_candidates(self.graphs[(i, "pre")])))
            for i in iters
        }

    def generate_extensions(self) -> tuple[bool, list[str]]:
        assert self.molly is not None
        achieved = sum(self.achieved_pre_goal_counts().values())
        all_achieved = achieved >= len(self.molly.runs)
        if all_achieved:
            return True, []
        return False, self.extension_suggestions()
