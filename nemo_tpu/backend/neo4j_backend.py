"""Neo4jBackend: GraphBackend over a live Neo4j server via Bolt.

This is the rebuild of the reference's only backend (the `Neo4J` struct,
graphing/pre-post-prov.go:16-20, speaking Bolt through its vendored Go
driver).  The storage model is identical — one `:Goal` node per goal with
props {id, run, condition, label, table, time, condition_holds}
(pre-post-prov.go:27-58), one `:Rule` per rule (:90-118), `[:DUETO]` edges
(:150-195), simplified shadow graphs at run 1000+i (preprocessing.go:15) and
diff graphs at 2000+i (differential-provenance.go:40).

Differences from the reference mechanics (behavior preserved; see SURVEY.md
§7 step 2, which calls these out as pure implementation details):

  * bulk loads are batched `UNWIND $rows CREATE` statements instead of one
    Bolt round-trip per node/edge (the reference's dominant cost,
    pre-post-prov.go:36-58) — count verification after each bulk load is
    kept (:84-86, :144-146, :208-210);
  * the APOC-export → `docker exec sed` → re-import dance used for shadow-run
    copies (preprocessing.go:17-57, differential-provenance.go:22-79) is
    replaced by in-process id rewriting + parameterized CREATE;
  * a `seq` property on nodes/edges preserves insertion order across pulls,
    making every downstream ordering deterministic (the reference's map
    iteration makes its own output order nondeterministic — SURVEY.md §7
    hard part 5);
  * host-side passes (chain components, diff closure, trigger/prototype/
    correction synthesis) reuse the same shared analysis code as the other
    backends, exactly as the reference runs them in Go on query results.

Every statement carries a `// nemo:<verb>` marker comment; the in-process
fake server used by the tests dispatches on it (tests/test_neo4j_backend.py),
which lets the full backend run end-to-end without a Neo4j container.
"""

from __future__ import annotations

from nemo_tpu.analysis.corrections import synthesize_corrections, synthesize_extensions
from nemo_tpu.analysis.protos import intersect_proto, missing_from, union_proto, wrap_code
from nemo_tpu.analysis.queries import (
    extension_candidates,
    find_post_triggers,
    find_pre_triggers,
)
from nemo_tpu.backend.base import GraphBackend
from nemo_tpu.backend.bolt import BoltConnection
from nemo_tpu.graphs.pgraph import PGraph, PNode
from nemo_tpu.ingest.datatypes import MissingEvent
from nemo_tpu.ingest.molly import MollyOutput
from nemo_tpu.report.dot import DotGraph
from nemo_tpu.report.figures import create_diff_dot, create_dot

CLEAN_OFFSET = 1000
DIFF_OFFSET = 2000

# --------------------------------------------------------------------- Cypher

Q_WIPE = "// nemo:wipe\nMATCH (n) DETACH DELETE n"

# Uniqueness constraints + run indexes, created once per session
# (pre-post-prov.go:66-81, :126-141; Neo4j 3.x syntax).
Q_CONSTRAINTS = [
    "// nemo:constraint_goal\nCREATE CONSTRAINT ON (g:Goal) ASSERT g.id IS UNIQUE",
    "// nemo:constraint_rule\nCREATE CONSTRAINT ON (r:Rule) ASSERT r.id IS UNIQUE",
    "// nemo:index_goal_run\nCREATE INDEX ON :Goal(run)",
    "// nemo:index_rule_run\nCREATE INDEX ON :Rule(run)",
]

Q_LOAD_GOALS = """// nemo:load_goals
UNWIND $rows AS row
CREATE (g:Goal {id: row.id, run: $run, condition: $condition, label: row.label,
                table: row.table, time: row.time, condition_holds: row.condition_holds,
                seq: row.seq})"""

Q_LOAD_RULES = """// nemo:load_rules
UNWIND $rows AS row
CREATE (r:Rule {id: row.id, run: $run, condition: $condition, label: row.label,
                table: row.table, type: row.type, seq: row.seq})"""

# Edges split by direction so every MATCH is label-scoped and can use the
# :Goal(id)/:Rule(id) uniqueness indexes — the same goal->rule / rule->goal
# split the reference makes by inspecting the From id (pre-post-prov.go:150-195).
Q_LOAD_EDGES_GR = """// nemo:load_edges_gr
UNWIND $rows AS row
MATCH (a:Goal {id: row.src}) MATCH (b:Rule {id: row.dst})
MERGE (a)-[e:DUETO]->(b) SET e.seq = row.seq"""

Q_LOAD_EDGES_RG = """// nemo:load_edges_rg
UNWIND $rows AS row
MATCH (a:Rule {id: row.src}) MATCH (b:Goal {id: row.dst})
MERGE (a)-[e:DUETO]->(b) SET e.seq = row.seq"""

Q_COUNT_GOALS = """// nemo:count_goals
MATCH (n:Goal {run: $run, condition: $condition}) RETURN count(n)"""

Q_COUNT_RULES = """// nemo:count_rules
MATCH (n:Rule {run: $run, condition: $condition}) RETURN count(n)"""

Q_COUNT_EDGES = """// nemo:count_edges
MATCH (a:Goal {run: $run, condition: $condition})-[e:DUETO]->() RETURN count(e)
UNION ALL
MATCH (a:Rule {run: $run, condition: $condition})-[e:DUETO]->() RETURN count(e)"""

# Condition marking (pre-post-prov.go:220-243): from the root goal of the
# condition's own table, two hops down, mark every goal of the condition
# table or of a grandchild goal's table.
Q_MARK_CONDITION = """// nemo:mark_condition
MATCH (root:Goal {run: $run, condition: $condition, table: $condition})
WHERE NOT ( ()-[:DUETO]->(root) )
MATCH (root)-[:DUETO]->(r:Rule {run: $run, condition: $condition, table: $condition})
      -[:DUETO]->(g:Goal {run: $run, condition: $condition})
WITH collect(DISTINCT g.table) + [$condition] AS tables, $run AS run, $condition AS cond
MATCH (x:Goal {run: run, condition: cond}) WHERE x.table IN tables
SET x.condition_holds = true"""

# Neo4j requires identical column names across UNION arms; alias the
# kind-literal column ('Goal' vs 'Rule') explicitly.
Q_PULL_NODES = """// nemo:pull_nodes
MATCH (n:Goal {run: $run, condition: $condition})
RETURN n.id AS id, 'Goal' AS kind, n.label AS label, n.table AS table,
       n.time AS time, n.type AS type, n.condition_holds AS condition_holds,
       n.seq AS seq
UNION ALL
MATCH (n:Rule {run: $run, condition: $condition})
RETURN n.id AS id, 'Rule' AS kind, n.label AS label, n.table AS table,
       n.time AS time, n.type AS type, n.condition_holds AS condition_holds,
       n.seq AS seq"""

Q_PULL_EDGES = """// nemo:pull_edges
MATCH (a:Goal {run: $run, condition: $condition})-[e:DUETO]->(b)
RETURN a.id, b.id, e.seq
UNION ALL
MATCH (a:Rule {run: $run, condition: $condition})-[e:DUETO]->(b)
RETURN a.id, b.id, e.seq"""

# Rules kept by the clean copy: >=1 incoming and >=1 outgoing edge (the
# degree formulation of the Goal-[*0..]->Goal path restriction,
# preprocessing.go:17-27; see base.py).
Q_CLEAN_KEPT_RULES = """// nemo:clean_kept_rules
MATCH (r:Rule {run: $run, condition: $condition})
WHERE ( ()-[:DUETO]->(r) ) AND ( (r)-[:DUETO]->() )
RETURN r.id ORDER BY r.seq"""

# Antecedent achieved: any goal of the simplified antecedent graph holds
# (prototype.go:13-15, queried on shadow run 1000+i).
Q_ACHIEVED_PRE = """// nemo:achieved_pre
MATCH (g:Goal {run: $run, condition: 'pre'})
WHERE g.condition_holds RETURN count(g)"""

# Prototype rule tables (prototype.go:11-24, corrected semantics per
# SURVEY.md §7): rules >=1 hop below an in-degree-0 goal root that have a
# rule descendant or a reachable rule ancestor; min path length per table.
Q_PROTO_TABLES = """// nemo:proto_tables
MATCH (root:Goal {run: $run, condition: $condition})
WHERE NOT ( ()-[:DUETO]->(root) )
MATCH p = (root)-[:DUETO*1..]->(r:Rule)
WHERE ( (r)-[:DUETO*1..]->(:Rule) )
   OR ( (root)-[:DUETO*1..]->(:Rule)-[:DUETO*1..]->(r) )
RETURN r.table, min(length(p))"""

Q_CLEAN_RULE_TABLES = """// nemo:clean_rule_tables
MATCH (r:Rule {run: $run, condition: $condition})
RETURN DISTINCT r.table"""

# Extensions precheck (extensions.go:25-50): count holding top-level
# antecedent goals across all raw runs (run < 1000).
Q_COUNT_PRE_HOLDS = """// nemo:count_pre_holds
MATCH (g:Goal {condition: 'pre', table: 'pre'})
WHERE g.condition_holds AND g.run < 1000
RETURN count(g)"""


class Neo4jBackend(GraphBackend):
    """GraphBackend speaking Bolt to a Neo4j server (reference parity
    backend; the baseline the TPU backend is measured against)."""

    def __init__(self, auth: tuple[str, str] | None = None) -> None:
        self.molly: MollyOutput | None = None
        self.conn1: BoltConnection | None = None
        self.conn2: BoltConnection | None = None
        self.auth = auth
        self._pull_cache: dict[tuple[int, str], PGraph] = {}

    # ------------------------------------------------------------------ setup

    def init_graph_db(self, conn: str, molly: MollyOutput) -> None:
        """Open the two Bolt connections (reference opens Conn1/Conn2,
        graphing/helpers.go:38-49; no docker lifecycle here — the server is
        expected to be running at `conn`) and reset the store."""
        self.molly = molly
        self._pull_cache = {}
        self.conn1 = BoltConnection(conn, auth=self.auth)
        self.conn2 = BoltConnection(conn, auth=self.auth)
        self.conn1.exec(Q_WIPE)
        for stmt in Q_CONSTRAINTS:
            self.conn1.exec(stmt)

    def close_db(self) -> None:
        for c in (self.conn1, self.conn2):
            if c is not None:
                c.close()
        self.conn1 = self.conn2 = None
        self.molly = None
        self._pull_cache = {}

    # ------------------------------------------------------------------- load

    def _load_graph(self, run: int, cond: str, g: PGraph) -> None:
        """Bulk-load one graph under (run, cond) with count verification
        (pre-post-prov.go:25-213)."""
        assert self.conn1 is not None
        goals = [
            {
                "id": n.id,
                "label": n.label,
                "table": n.table,
                "time": n.time,
                "condition_holds": n.cond_holds,
                "seq": i,
            }
            for i, n in enumerate(g.nodes.values())
            if n.is_goal
        ]
        rules = [
            {"id": n.id, "label": n.label, "table": n.table, "type": n.type, "seq": i}
            for i, n in enumerate(g.nodes.values())
            if not n.is_goal
        ]
        edges_gr = [
            {"src": s, "dst": d, "seq": i}
            for i, (s, d) in enumerate(g.edge_order)
            if g.nodes[s].is_goal
        ]
        edges_rg = [
            {"src": s, "dst": d, "seq": i}
            for i, (s, d) in enumerate(g.edge_order)
            if not g.nodes[s].is_goal
        ]
        params = {"run": run, "condition": cond}
        if goals:
            self.conn1.exec(Q_LOAD_GOALS, {**params, "rows": goals})
        if rules:
            self.conn1.exec(Q_LOAD_RULES, {**params, "rows": rules})
        if edges_gr:
            self.conn1.exec(Q_LOAD_EDGES_GR, {**params, "rows": edges_gr})
        if edges_rg:
            self.conn1.exec(Q_LOAD_EDGES_RG, {**params, "rows": edges_rg})
        n_nodes = (
            self.conn1.exec(Q_COUNT_GOALS, params)[0][0]
            + self.conn1.exec(Q_COUNT_RULES, params)[0][0]
        )
        if n_nodes != len(g.nodes):
            raise RuntimeError(
                f"node count mismatch for run {run} {cond}: {n_nodes} != {len(g.nodes)}"
            )
        n_edges = sum(row[0] for row in self.conn1.exec(Q_COUNT_EDGES, params))
        if n_edges != len(g.edge_order):
            raise RuntimeError(
                f"edge count mismatch for run {run} {cond}: {n_edges} != {len(g.edge_order)}"
            )

    def load_raw_provenance(self) -> None:
        assert self.molly is not None and self.conn1 is not None
        from nemo_tpu.graphs.pgraph import build_pgraph

        for run in self.molly.runs:
            for cond, prov in (("pre", run.pre_prov), ("post", run.post_prov)):
                self._load_graph(run.iteration, cond, build_pgraph(prov))
                self.conn1.exec(
                    Q_MARK_CONDITION, {"run": run.iteration, "condition": cond}
                )

    # ------------------------------------------------------------------- pull

    def _pull_graph(self, run: int, cond: str) -> PGraph:
        """Materialize one stored graph, insertion order restored host-side
        from the seq property (the UNION of label-scoped matches has no
        server-side order)."""
        assert self.conn1 is not None
        key = (run, cond)
        cached = self._pull_cache.get(key)
        if cached is not None:
            return cached
        g = PGraph()
        node_rows = self.conn1.exec(Q_PULL_NODES, {"run": run, "condition": cond})
        for nid, kind, label, table, time, typ, holds, _seq in sorted(
            node_rows, key=lambda r: r[7]
        ):
            g.add_node(
                PNode(
                    id=nid,
                    is_goal=kind == "Goal",
                    label=label,
                    table=table,
                    time=time or "",
                    type=typ or "",
                    cond_holds=bool(holds),
                )
            )
        edge_rows = self.conn1.exec(Q_PULL_EDGES, {"run": run, "condition": cond})
        for src, dst, _seq in sorted(edge_rows, key=lambda r: r[2]):
            g.add_edge(src, dst)
        self._pull_cache[key] = g
        return g

    # --------------------------------------------------------------- simplify

    def simplify_prov(self, iters: list[int]) -> None:
        """Clean copy + @next chain contraction into shadow run 1000+i
        (preprocessing.go:351-387).  The kept-rule selection runs as Cypher;
        id rewriting happens in-process (replacing the reference's
        docker-exec sed, preprocessing.go:33-54); the contraction reuses the
        shared deterministic component pass on the shadow graph and writes
        the result back."""
        from nemo_tpu.backend.python_ref import PythonBackend

        for i in iters:
            for cond in ("pre", "post"):
                assert self.conn1 is not None
                kept_rule_ids = {
                    row[0]
                    for row in self.conn1.exec(
                        Q_CLEAN_KEPT_RULES, {"run": i, "condition": cond}
                    )
                }
                raw = self._pull_graph(i, cond)
                clean = PythonBackend._clean_copy(raw, i, cond, kept_rule_ids=kept_rule_ids)
                # Chain contraction: shared deterministic component pass
                # (python_ref._collapse_next_chains == kernel semantics).
                PythonBackend._collapse_next_chains(clean, i, cond)
                self._load_graph(CLEAN_OFFSET + i, cond, clean)
                self._pull_cache[(CLEAN_OFFSET + i, cond)] = clean

    # ------------------------------------------------------------- prototypes

    def _achieved_pre(self, iteration: int) -> bool:
        assert self.conn1 is not None
        n = self.conn1.exec(Q_ACHIEVED_PRE, {"run": CLEAN_OFFSET + iteration})[0][0]
        return n > 0

    def proto_rule_tables(self, iteration: int, condition: str) -> list[str]:
        """Cypher variable-length path query (prototype.go:11-24) + the
        canonical (min rule-depth, table) host ordering."""
        assert self.conn2 is not None
        if not self._achieved_pre(iteration):
            return []
        rows = self.conn2.exec(
            Q_PROTO_TABLES, {"run": CLEAN_OFFSET + iteration, "condition": condition}
        )
        by_table: dict[str, int] = {}
        for table, min_len in rows:
            rule_depth = (int(min_len) + 1) // 2  # hops alternate goal/rule
            prev = by_table.get(table)
            if prev is None or rule_depth < prev:
                by_table[table] = rule_depth
        return [t for t, _ in sorted(by_table.items(), key=lambda kv: (kv[1], kv[0]))]

    def clean_rule_tables(self, iteration: int, condition: str) -> set[str]:
        assert self.conn2 is not None
        rows = self.conn2.exec(
            Q_CLEAN_RULE_TABLES,
            {"run": CLEAN_OFFSET + iteration, "condition": condition},
        )
        return {r[0] for r in rows}

    def create_prototypes(
        self, success_iters: list[int], failed_iters: list[int]
    ) -> tuple[list[str], list[list[str]], list[str], list[list[str]]]:
        per_run = [self.proto_rule_tables(i, "post") for i in success_iters]
        inter = intersect_proto(per_run, "post")
        union = union_proto(per_run, "post")
        inter_miss, union_miss = [], []
        for f in failed_iters:
            present = self.clean_rule_tables(f, "post")
            inter_miss.append(missing_from(inter, present))
            union_miss.append(missing_from(union, present))
        return wrap_code(inter), inter_miss, wrap_code(union), union_miss

    # ------------------------------------------------------------------- pull

    def pull_pre_post_prov(
        self, iters: list[int] | None = None
    ) -> tuple[list[DotGraph], list[DotGraph], list[DotGraph], list[DotGraph]]:
        assert self.molly is not None
        run_ids = [r.iteration for r in self.molly.runs] if iters is None else list(iters)
        pre, post, pre_clean, post_clean = [], [], [], []
        for i in run_ids:
            pre.append(create_dot(self._pull_graph(i, "pre"), "pre"))
            post.append(create_dot(self._pull_graph(i, "post"), "post"))
            pre_clean.append(create_dot(self._pull_graph(CLEAN_OFFSET + i, "pre"), "pre"))
            post_clean.append(
                create_dot(self._pull_graph(CLEAN_OFFSET + i, "post"), "post")
            )
        return pre, post, pre_clean, post_clean

    # ------------------------------------------------------------------- diff

    def create_naive_diff_prov(
        self,
        symmetric: bool,
        failed_iters: list[int],
        success_post_dot: DotGraph,
        dot_iters: list[int] | None = None,
    ) -> tuple[list[DotGraph], list[DotGraph], list[list[MissingEvent]]]:
        """Good-minus-bad per failed run (differential-provenance.go:18-243).
        The diff subgraph is computed on the pulled good graph with the shared
        closure logic, stored to shadow run 2000+f (the reference's
        export/sed/import becomes rewrite+CREATE), and the frontier reuses the
        shared longest-path pass."""
        from nemo_tpu.backend.python_ref import PythonBackend

        if not failed_iters:
            return [], [], []
        g = self.good_run_iter()
        helper = PythonBackend()
        helper.molly = self.molly
        helper.graphs = {
            (g, "post"): self._pull_graph(g, "post"),
        }
        dot_set = set(failed_iters if dot_iters is None else dot_iters)
        diff_dots, failed_dots, missing_events = [], [], []
        for f in failed_iters:
            helper.graphs[(f, "post")] = self._pull_graph(f, "post")
            diff = helper.diff_graph(f)
            self._load_graph(DIFF_OFFSET + f, "post", diff)
            missing = helper._diff_missing(diff)
            missing_events.append(missing)
            if f not in dot_set:
                continue
            diff_dot, failed_dot = create_diff_dot(
                DIFF_OFFSET + f,
                diff,
                helper.graphs[(f, "post")],
                g,
                success_post_dot,
                missing,
            )
            diff_dots.append(diff_dot)
            failed_dots.append(failed_dot)
        return diff_dots, failed_dots, missing_events

    # ------------------------------------------------------- corrections etc.

    def generate_corrections(self) -> list[str]:
        g = self.good_run_iter()
        pre_triggers = find_pre_triggers(self._pull_graph(g, "pre"))
        post_triggers = find_post_triggers(self._pull_graph(g, "post"))
        return synthesize_corrections(pre_triggers, post_triggers)

    def generate_extensions(self) -> tuple[bool, list[str]]:
        assert self.molly is not None and self.conn1 is not None
        achieved = self.conn1.exec(Q_COUNT_PRE_HOLDS)[0][0]
        all_achieved = achieved >= len(self.molly.runs)
        if all_achieved:
            return True, []
        candidates = extension_candidates(self._pull_graph(self.baseline_run_iter(), "pre"))
        return False, synthesize_extensions(candidates)
