"""Client for the TPU sidecar: packs locally (natively when possible),
analyzes remotely.

Failure handling (SURVEY.md §5 — the reference has none; everything is
log.Fatalf): health-gated connect with deadline, bounded retries with
exponential backoff on UNAVAILABLE, and chunk ordinals verified on receipt.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import grpc
import numpy as np

from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb
from nemo_tpu.service.server import SERVICE


class SidecarError(RuntimeError):
    pass


@dataclass
class RemoteAnalyzer:
    """Thin, retrying client over the NemoAnalysis service."""

    target: str = "127.0.0.1:50051"
    timeout: float = 300.0
    retries: int = 3

    def __post_init__(self):
        self._channel = grpc.insecure_channel(
            self.target,
            options=[
                ("grpc.max_receive_message_length", 1 << 30),
                ("grpc.max_send_message_length", 1 << 30),
            ],
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        self._analyze = self._channel.unary_unary(
            f"/{SERVICE}/Analyze",
            request_serializer=pb.AnalyzeRequest.SerializeToString,
            response_deserializer=pb.AnalyzeResponse.FromString,
        )
        self._analyze_stream = self._channel.stream_stream(
            f"/{SERVICE}/AnalyzeStream",
            request_serializer=pb.AnalyzeRequest.SerializeToString,
            response_deserializer=pb.AnalyzeResponse.FromString,
        )
        self._kernel = self._channel.unary_unary(
            f"/{SERVICE}/Kernel",
            request_serializer=pb.KernelRequest.SerializeToString,
            response_deserializer=pb.KernelResponse.FromString,
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- health

    def health(self, timeout: float = 10.0) -> dict:
        resp = self._call(self._health, pb.HealthRequest(), timeout)
        return {
            "platform": resp.platform,
            "device_count": resp.device_count,
            "version": resp.version,
        }

    def wait_ready(self, deadline: float = 30.0) -> dict:
        """Poll Health until the sidecar answers (startup gate).  Single
        attempt per poll — retry policy here is the loop itself, not _call."""
        end = time.monotonic() + deadline
        last: Exception | None = None
        while time.monotonic() < end:
            try:
                resp = self._health(pb.HealthRequest(), timeout=2.0)
                return {
                    "platform": resp.platform,
                    "device_count": resp.device_count,
                    "version": resp.version,
                }
            except grpc.RpcError as ex:
                last = ex
                time.sleep(0.2)
        raise SidecarError(f"sidecar not ready after {deadline}s: {last}")

    def _call(self, method, request, timeout: float | None = None):
        delay = 0.2
        for attempt in range(self.retries):
            try:
                return method(request, timeout=timeout or self.timeout)
            except grpc.RpcError as ex:
                if ex.code() != grpc.StatusCode.UNAVAILABLE or attempt == self.retries - 1:
                    raise
                time.sleep(delay)
                delay *= 2
        raise SidecarError("unreachable")

    # ------------------------------------------------------------- kernel

    def kernel(self, verb: str, arrays: dict, params: dict) -> dict[str, np.ndarray]:
        """One named device-kernel call on the sidecar (ServiceBackend path)."""
        req = codec.kernel_request_to_pb(verb, arrays, params)
        return codec.kernel_response_from_pb(self._call(self._kernel, req))

    # ------------------------------------------------------------ analyze

    def analyze(self, pre, post, static: dict) -> dict[str, np.ndarray]:
        """One fused analysis step on the sidecar's device."""
        req = pb.AnalyzeRequest(
            pre=codec.batch_arrays_to_pb(pre),
            post=codec.batch_arrays_to_pb(post),
        )
        req.static.CopyFrom(codec.static_to_pb(static))
        return codec.outputs_from_pb(self._call(self._analyze, req))

    def analyze_chunks(
        self, chunks: list[tuple[object, object, dict]]
    ) -> list[dict[str, np.ndarray]]:
        """Stream chunks through the bidi RPC; returns per-chunk outputs in
        submission order (ordinals are verified)."""

        def requests():
            for i, (pre, post, static) in enumerate(chunks):
                req = pb.AnalyzeRequest(
                    pre=codec.batch_arrays_to_pb(pre),
                    post=codec.batch_arrays_to_pb(post),
                    chunk=i,
                )
                req.static.CopyFrom(codec.static_to_pb(static))
                yield req

        out: list[dict[str, np.ndarray] | None] = [None] * len(chunks)
        for resp in self._analyze_stream(requests(), timeout=self.timeout):
            if not 0 <= resp.chunk < len(chunks):
                raise SidecarError(f"bad chunk ordinal {resp.chunk}")
            out[resp.chunk] = codec.outputs_from_pb(resp)
        missing = [i for i, o in enumerate(out) if o is None]
        if missing:
            raise SidecarError(f"missing responses for chunks {missing}")
        return out  # type: ignore[return-value]


@dataclass
class RemoteExecutor:
    """Drop-in for backend.jax_backend.LocalExecutor that runs every kernel
    on the sidecar: same (verb, arrays, params) contract, carried over the
    Kernel RPC.  Owns its RemoteAnalyzer; close() releases the channel."""

    target: str = "127.0.0.1:50051"
    ready_deadline: float = 30.0

    def __post_init__(self):
        self._client = RemoteAnalyzer(target=self.target)
        try:
            self._client.wait_ready(self.ready_deadline)
        except BaseException:
            # Don't leak the channel (and its worker threads) when the
            # sidecar is unreachable.
            self._client.close()
            raise

    def run(self, verb: str, arrays: dict, params: dict) -> dict[str, np.ndarray]:
        return self._client.kernel(verb, arrays, params)

    def close(self) -> None:
        self._client.close()


def analyze_dirs(
    target: str, molly_dirs: list[str], queue_depth: int = 2
) -> tuple[list[dict[str, np.ndarray]], dict[str, float]]:
    """Pipelined multi-corpus analysis with TRUE ingest/compute overlap
    (SURVEY.md §2.3 pipeline-parallel row; VERDICT r1 item 5).

    A producer thread packs each Molly directory (natively when available)
    and feeds a bounded queue; the bidi AnalyzeStream RPC consumes from the
    queue, so directory k+1 is parsing/packing on the host WHILE directory
    k executes on the sidecar's device.  queue_depth bounds host memory
    (backpressure).  Returns (per-directory outputs, timing dict with
    pack_s, stream_s, wall_s — overlap win = pack_s + stream_s - wall_s
    when positive).
    """
    import queue
    import threading

    t_wall0 = time.perf_counter()
    timings = {"pack_s": 0.0, "stream_s": 0.0, "wall_s": 0.0}
    q: queue.Queue = queue.Queue(maxsize=max(1, queue_depth))
    _END = object()

    def producer() -> None:
        from nemo_tpu.ingest.native import pack_molly_dir

        try:
            for i, d in enumerate(molly_dirs):
                t0 = time.perf_counter()
                packed = pack_molly_dir(d)
                timings["pack_s"] += time.perf_counter() - t0
                q.put((i, packed))
        except BaseException as ex:  # surface in the consumer
            q.put(ex)
        finally:
            q.put(_END)

    threading.Thread(target=producer, daemon=True, name="nemo-pack").start()

    def requests():
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            i, (pre, post, static) = item
            req = pb.AnalyzeRequest(
                pre=codec.batch_arrays_to_pb(pre),
                post=codec.batch_arrays_to_pb(post),
                chunk=i,
            )
            req.static.CopyFrom(codec.static_to_pb(static))
            yield req

    results: list[dict[str, np.ndarray] | None] = [None] * len(molly_dirs)
    with RemoteAnalyzer(target=target) as client:
        client.wait_ready()
        t0 = time.perf_counter()
        for resp in client._analyze_stream(requests(), timeout=client.timeout):
            if not 0 <= resp.chunk < len(molly_dirs):
                raise SidecarError(f"bad chunk ordinal {resp.chunk}")
            results[resp.chunk] = codec.outputs_from_pb(resp)
        timings["stream_s"] = time.perf_counter() - t0
    missing = [i for i, o in enumerate(results) if o is None]
    if missing:
        raise SidecarError(f"missing responses for directories {missing}")
    timings["wall_s"] = time.perf_counter() - t_wall0
    return results, timings  # type: ignore[return-value]


def analyze_dir(target: str, molly_dir: str, chunk_runs: int = 0) -> dict[str, np.ndarray]:
    """Native-pack a Molly directory and analyze it remotely, optionally
    streamed in chunks of chunk_runs runs.

    Chunked results are merged to be equivalent to one unchunked call: every
    chunk gets the corpus's good run (row 0) prepended so the differential
    provenance baseline (analysis_step diffs against its batch's row 0) and
    the prototype reductions see it; the duplicate row is dropped from
    per-run outputs and the cross-chunk reductions are re-combined.
    """
    import jax

    from nemo_tpu.ingest.native import pack_molly_dir

    pre, post, static = pack_molly_dir(molly_dir)
    b = int(np.asarray(pre.is_goal).shape[0])
    with RemoteAnalyzer(target=target) as client:
        client.wait_ready()
        if not chunk_runs or chunk_runs >= b:
            return client.analyze(pre, post, static)

        def rows(arrays, s, e, with_good: bool):
            if with_good:
                return jax.tree_util.tree_map(
                    lambda x: np.concatenate([np.asarray(x[:1]), np.asarray(x[s:e])]), arrays
                )
            return jax.tree_util.tree_map(lambda x: x[s:e], arrays)

        spans = [(s, min(s + chunk_runs, b)) for s in range(0, b, chunk_runs)]
        chunks = [
            (rows(pre, s, e, s > 0), rows(post, s, e, s > 0), static) for s, e in spans
        ]
        results = client.analyze_chunks(chunks)

    from nemo_tpu.models.pipeline_model import CORPUS_REDUCTIONS

    merged: dict[str, np.ndarray] = {}
    for key in results[0]:
        how = CORPUS_REDUCTIONS.get(key)
        if how == "and":
            merged[key] = np.logical_and.reduce([r[key] for r in results])
        elif how == "or":
            merged[key] = np.logical_or.reduce([r[key] for r in results])
        else:
            # Per-run rows: drop the prepended good-run row of chunks > 0.
            # Guard against an unregistered reduction output silently being
            # concatenated as if it were per-run (CORPUS_REDUCTIONS contract).
            for (s, e), r in zip(spans, results):
                expected = (e - s) + (1 if s > 0 else 0)
                if r[key].shape[0] != expected:
                    raise SidecarError(
                        f"output {key!r} is not per-run shaped "
                        f"(got leading dim {r[key].shape[0]}, batch {expected}); "
                        "register it in models.pipeline_model.CORPUS_REDUCTIONS"
                    )
            parts = [results[0][key]] + [r[key][1:] for r in results[1:]]
            merged[key] = np.concatenate(parts, axis=0)
    return merged
