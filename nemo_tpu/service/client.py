"""Client for the TPU sidecar: packs locally (natively when possible),
analyzes remotely.

Failure handling (SURVEY.md §5 — the reference has none; everything is
log.Fatalf): health-gated connect with deadline, bounded retries with
exponential backoff on UNAVAILABLE, and chunk ordinals verified on receipt.
"""

from __future__ import annotations

import json as _json
import time
from dataclasses import dataclass

import grpc
import numpy as np

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb
from nemo_tpu.service.server import SERVICE
from nemo_tpu.utils.backoff import RPC_POLICY

_log = obs_log.get_logger("nemo.client")


class SidecarError(RuntimeError):
    pass


def _trace_metadata() -> tuple | None:
    """Outgoing gRPC metadata propagating this process's trace context, or
    None when tracing is off.  The sidecar answers a traced request with
    its own spans in 'nemo-spans-bin' trailing metadata (service/server.py)
    — collected by _adopt_remote below — so one client trace file shows
    both sides of every RPC under one trace id."""
    tid = obs.trace_id()
    if tid is None:
        return None
    return (("nemo-trace-id", tid),)


def _metadata_value(md, key: str):
    for k, v in md or ():
        if k == key:
            return v
    return None


def _drive_stream(
    stream_callable, requests_iter, timeout: float, target: str, out: list,
    extra_md: tuple = (),
) -> None:
    """Drive one AnalyzeStream call under the trace contract shared by
    analyze_chunks and the pipelined producer paths: one rpc:AnalyzeStream
    span, trace/tenant metadata attached only when present (bare calls
    keep the bare signature — test fakes and old stubs stay compatible),
    per-chunk ordinal checks filling `out`, and the sidecar's spans adopted
    from trailing metadata once the stream completes."""
    n = len(out)
    with obs.span("rpc:AnalyzeStream", target=target, chunks=n):
        md = (_trace_metadata() or ()) + tuple(extra_md or ())
        stream = stream_callable(
            requests_iter, timeout=timeout, **({"metadata": md} if md else {})
        )
        for resp in stream:
            if not 0 <= resp.chunk < n:
                raise SidecarError(f"bad chunk ordinal {resp.chunk}")
            out[resp.chunk] = codec.outputs_from_pb(resp)
        _adopt_remote(stream)


def _adopt_remote(call) -> None:
    """Merge the sidecar's spans (trailing metadata) into the local trace."""
    t = obs.tracer()
    if t is None:
        return
    try:
        raw = _metadata_value(call.trailing_metadata(), "nemo-spans-bin")
    except Exception:
        return
    if not raw:
        return
    try:
        spans = _json.loads(raw.decode("utf-8") if isinstance(raw, bytes) else raw)
    except (ValueError, UnicodeDecodeError):
        return
    t.adopt(spans, process_name="nemo-sidecar")


@dataclass
class RemoteAnalyzer:
    """Thin, retrying client over the NemoAnalysis service.

    ``tenant`` identifies this client to the sidecar's admission
    controller (per-tenant fairness and metrics, ISSUE 8) via the
    ``nemo-tenant`` request metadata; defaults to ``$NEMO_TENANT`` or the
    shared anonymous tenant."""

    target: str = "127.0.0.1:50051"
    timeout: float = 300.0
    retries: int = 3
    tenant: str | None = None

    def __post_init__(self):
        import os as _os

        if self.tenant is None:
            self.tenant = _os.environ.get("NEMO_TENANT") or None
        self._channel = grpc.insecure_channel(
            self.target,
            options=[
                ("grpc.max_receive_message_length", 1 << 30),
                ("grpc.max_send_message_length", 1 << 30),
                # The sidecar's span trailing metadata (traced runs) can
                # reach ~1 MB (server _SpanCollection.MAX_BYTES); the
                # default metadata cap is 8 KB.
                ("grpc.max_metadata_size", 2 << 20),
            ],
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        self._analyze = self._channel.unary_unary(
            f"/{SERVICE}/Analyze",
            request_serializer=pb.AnalyzeRequest.SerializeToString,
            response_deserializer=pb.AnalyzeResponse.FromString,
        )
        self._analyze_stream = self._channel.stream_stream(
            f"/{SERVICE}/AnalyzeStream",
            request_serializer=pb.AnalyzeRequest.SerializeToString,
            response_deserializer=pb.AnalyzeResponse.FromString,
        )
        self._kernel = self._channel.unary_unary(
            f"/{SERVICE}/Kernel",
            request_serializer=pb.KernelRequest.SerializeToString,
            response_deserializer=pb.KernelResponse.FromString,
        )
        # JSON request (a directory path — see server.analyze_dir), standard
        # AnalyzeResponse back; generic serializers need no protoc.
        self._analyze_dir = self._channel.unary_unary(
            f"/{SERVICE}/AnalyzeDir",
            request_serializer=lambda d: _json.dumps(d).encode("utf-8"),
            response_deserializer=pb.AnalyzeResponse.FromString,
        )
        # Ad-hoc query RPC (ISSUE 20): JSON both ways.
        self._query = self._channel.unary_unary(
            f"/{SERVICE}/Query",
            request_serializer=lambda d: _json.dumps(d).encode("utf-8"),
            response_deserializer=lambda b: _json.loads(b.decode("utf-8")),
        )
        # Server-streaming variant: JSON request, JSON event stream back
        # (results carry the serialized AnalyzeResponse base64-embedded).
        self._analyze_dir_stream = self._channel.unary_stream(
            f"/{SERVICE}/AnalyzeDirStream",
            request_serializer=lambda d: _json.dumps(d).encode("utf-8"),
            response_deserializer=lambda b: _json.loads(b.decode("utf-8")),
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- health

    def health(self, timeout: float = 10.0) -> dict:
        resp, call = self._call(self._health, pb.HealthRequest(), timeout, name="Health")
        out = {
            "platform": resp.platform,
            "device_count": resp.device_count,
            "version": resp.version,
        }
        # The sidecar ships its obs metrics snapshot in trailing metadata
        # (no proto change needed), so operators see device-side state —
        # dispatch counts, compile-cache hits, step latencies — through any
        # client's health() without SSH-ing to the sidecar host.
        try:
            raw = _metadata_value(call.trailing_metadata(), "nemo-metrics-bin")
            if raw:
                out["metrics"] = _json.loads(
                    raw.decode("utf-8") if isinstance(raw, bytes) else raw
                )
        except Exception:  # lint: allow-silent-except — optional metadata; an old server without it is still healthy
            pass
        return out

    def wait_ready(self, deadline: float = 30.0) -> dict:
        """Poll Health until the sidecar answers (startup gate).  Single
        attempt per poll — retry policy here is the loop itself, not _call."""
        end = time.monotonic() + deadline
        last: Exception | None = None
        while time.monotonic() < end:
            try:
                resp = self._health(pb.HealthRequest(), timeout=2.0)
                return {
                    "platform": resp.platform,
                    "device_count": resp.device_count,
                    "version": resp.version,
                }
            except grpc.RpcError as ex:
                last = ex
                time.sleep(0.2)
        raise SidecarError(f"sidecar not ready after {deadline}s: {last}")

    def _request_metadata(self) -> tuple | None:
        """Outgoing metadata: trace context plus the tenant identity the
        sidecar's admission controller schedules by."""
        md = _trace_metadata() or ()
        if self.tenant:
            md = md + (("nemo-tenant", self.tenant),)
        return md or None

    def _call(self, method, request, timeout: float | None = None, name: str = "rpc"):
        """One unary RPC with bounded retries; returns (response, call) —
        with_call so trailing metadata (sidecar spans, metrics) is
        readable.  UNAVAILABLE retries and the RESOURCE_EXHAUSTED
        throttle path (admission rejection, ISSUE 8 — the sidecar's
        `nemo-retry-after-s` trailing-metadata hint, counted as
        `rpc.throttled`) share ONE jittered-exponential policy with a
        total retry BUDGET (utils/backoff.py:RPC_POLICY, ISSUE 9
        satellite): a server hint replaces the exponential term for that
        attempt (clamped by the policy), and cumulative waiting past the
        budget raises instead of accumulating unbounded latency.  Every
        attempt gets a span and a latency observation."""
        backoff = RPC_POLICY.session()
        md = self._request_metadata()
        for attempt in range(self.retries):
            try:
                t0 = time.perf_counter()
                with obs.span(
                    f"rpc:{name}", target=self.target, attempt=attempt,
                    trace_id=obs.trace_id(),
                ):
                    resp, call = method.with_call(
                        request, timeout=timeout or self.timeout, metadata=md
                    )
                dt = time.perf_counter() - t0
                obs.metrics.inc(f"rpc.calls.{name}")
                obs.metrics.observe(f"rpc.latency_s.{name}", dt)
                # Client-side slow-RPC watchdog (the kernel-dispatch twin in
                # backend/jax_backend.py): any RPC past NEMO_SLOW_DISPATCH_MS
                # logs its route and payload size — the tunnel stall /
                # pathological-signature tripwire for the two-process shape.
                slow_ms = obs_log.slow_dispatch_ms()
                if slow_ms and dt * 1000.0 > slow_ms:
                    obs.metrics.inc("watchdog.slow_rpc")
                    _log.warning(
                        "rpc.slow",
                        rpc=name,
                        target=self.target,
                        wall_ms=round(dt * 1000.0, 1),
                        threshold_ms=slow_ms,
                        # AnalyzeDir requests are JSON dicts, not protobufs;
                        # count wire bytes (utf-8), exactly like ByteSize.
                        request_bytes=request.ByteSize()
                        if hasattr(request, "ByteSize")
                        else len(_json.dumps(request).encode("utf-8")),
                        attempt=attempt,
                    )
                _adopt_remote(call)
                return resp, call
            except grpc.RpcError as ex:
                code = ex.code()
                # RESOURCE_EXHAUSTED is only the sidecar's admission
                # rejection when it carries the retry-after hint; grpc
                # itself uses the same code for DETERMINISTIC failures
                # (e.g. a message over the 1 GiB channel cap), which must
                # raise immediately — sleep-retrying an oversized payload
                # would mask the bug as server load.
                retry_after = None
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    try:
                        raw = _metadata_value(
                            ex.trailing_metadata(), "nemo-retry-after-s"
                        )
                        retry_after = float(raw) if raw else None
                    except Exception:
                        retry_after = None
                throttled = retry_after is not None
                if (
                    code != grpc.StatusCode.UNAVAILABLE and not throttled
                ) or attempt == self.retries - 1:
                    obs.metrics.inc("rpc.errors")
                    raise
                # Shared policy: the throttle hint (when present) replaces
                # the exponential term, clamped by the policy's max delay
                # so a wild hint cannot park the client; None means the
                # total retry budget is spent — fail now, loudly, instead
                # of waiting forever.
                wait = backoff.delay(hint_s=retry_after if throttled else None)
                if wait is None:
                    obs.metrics.inc("rpc.errors")
                    obs.metrics.inc("rpc.retry_budget_exhausted")
                    _log.warning(
                        "rpc.retry_budget_exhausted", rpc=name,
                        target=self.target, spent_s=round(backoff.spent_s, 1),
                    )
                    raise
                if throttled:
                    obs.metrics.inc("rpc.throttled")
                    _log.info(
                        "rpc.throttled", rpc=name, target=self.target,
                        retry_after_s=round(wait, 2), attempt=attempt,
                    )
                else:
                    obs.metrics.inc("rpc.retries")
                obs.metrics.inc("rpc.backoff_s", wait)
                time.sleep(wait)
        raise SidecarError("unreachable")

    # ------------------------------------------------------------- kernel

    def kernel(self, verb: str, arrays: dict, params: dict) -> dict[str, np.ndarray]:
        """One named device-kernel call on the sidecar (ServiceBackend path)."""
        req = codec.kernel_request_to_pb(verb, arrays, params)
        obs.metrics.inc("rpc.bytes_sent", req.ByteSize())
        resp, _ = self._call(self._kernel, req, name="Kernel")
        obs.metrics.inc("rpc.bytes_received", resp.ByteSize())
        return codec.kernel_response_from_pb(resp)

    # ------------------------------------------------------------ analyze

    def analyze(self, pre, post, static: dict) -> dict[str, np.ndarray]:
        """One fused analysis step on the sidecar's device."""
        req = pb.AnalyzeRequest(
            pre=codec.batch_arrays_to_pb(pre),
            post=codec.batch_arrays_to_pb(post),
        )
        req.static.CopyFrom(codec.static_to_pb(static))
        obs.metrics.inc("rpc.bytes_sent", req.ByteSize())
        resp, _ = self._call(self._analyze, req, name="Analyze")
        obs.metrics.inc("rpc.bytes_received", resp.ByteSize())
        return codec.outputs_from_pb(resp)

    def analyze_dir_remote(
        self,
        molly_dir: str,
        corpus_cache: str | None = None,
        result_cache: str | None = None,
    ) -> dict[str, np.ndarray]:
        """Server-side corpus analysis: ship only the DIRECTORY PATH; the
        sidecar ingests (consulting its own persistent corpus store, so
        repeated sessions over the same corpus mmap-load instead of
        re-parsing) and runs the fused step — or serves the whole response
        from its result cache when the stored corpus + statics are
        unchanged (zero device dispatches; the trailing-metadata
        ``nemo-rcache`` status lands in the ``rpc.analyze_dir_rcache.*``
        counters and a log record).  ``corpus_cache``/``result_cache`` can
        only OPT OUT ("off") for this request; enabling or redirecting the
        server-side caches is the sidecar operator's knob, and any other
        value is ignored server-side."""
        import os

        req: dict = {"dir": os.path.abspath(molly_dir)}
        if corpus_cache is not None:
            req["corpus_cache"] = corpus_cache
        if result_cache is not None:
            req["result_cache"] = result_cache
        obs.metrics.inc("rpc.bytes_sent", len(_json.dumps(req).encode("utf-8")))
        resp, call = self._call(self._analyze_dir, req, name="AnalyzeDir")
        obs.metrics.inc("rpc.bytes_received", resp.ByteSize())
        try:
            trailing = dict(call.trailing_metadata() or ())
        except Exception:
            trailing = {}
        status = trailing.get("nemo-rcache")
        if status:
            obs.metrics.inc(f"rpc.analyze_dir_rcache.{status}")
            if status == "hit":
                _log.info(
                    "rpc.analyze_dir_cached", dir=molly_dir, target=self.target
                )
        coalesce = trailing.get("nemo-coalesce")
        if coalesce:
            # "hit" = this request rode another client's identical
            # in-flight analysis (ISSUE 8 single-flight).
            obs.metrics.inc(f"rpc.analyze_dir_coalesce.{coalesce}")
        fleet = trailing.get("nemo-fleet")
        if fleet:
            # Cross-REPLICA single-flight status (ISSUE 14): "leader" ran
            # the fleet's one analysis, "follower" rode another replica's
            # via the shared cache tier.
            obs.metrics.inc(f"rpc.analyze_dir_fleet.{fleet}")
        return codec.outputs_from_pb(resp)

    def query_remote(
        self,
        molly_dir: str,
        query: str,
        corpus_cache: str | None = None,
        result_cache: str | None = None,
    ) -> dict:
        """Run one ad-hoc provenance query server-side (ISSUE 20): ship the
        directory path + query TEXT; the sidecar compiles and executes it
        on the batched kernels (nemo_tpu/query) and returns the JSON
        result document.  Trailing ``nemo-rcache``/``nemo-coalesce``
        statuses land in the ``rpc.query_rcache.*`` /
        ``rpc.query_coalesce.*`` counters; a malformed query raises
        INVALID_ARGUMENT carrying the parser's message."""
        import os

        req: dict = {"dir": os.path.abspath(molly_dir), "query": query}
        if corpus_cache is not None:
            req["corpus_cache"] = corpus_cache
        if result_cache is not None:
            req["result_cache"] = result_cache
        obs.metrics.inc("rpc.bytes_sent", len(_json.dumps(req).encode("utf-8")))
        doc, call = self._call(self._query, req, name="Query")
        obs.metrics.inc(
            "rpc.bytes_received", len(_json.dumps(doc).encode("utf-8"))
        )
        try:
            trailing = dict(call.trailing_metadata() or ())
        except Exception:
            trailing = {}
        status = trailing.get("nemo-rcache")
        if status:
            obs.metrics.inc(f"rpc.query_rcache.{status}")
        coalesce = trailing.get("nemo-coalesce")
        if coalesce:
            obs.metrics.inc(f"rpc.query_coalesce.{coalesce}")
        return doc

    def analyze_dir_stream(
        self, molly_dirs, corpus_cache=None, result_cache=None, watch=None
    ):
        """Server-streaming corpus analysis (ISSUE 8): ship the directory
        PATHS; the sidecar analyzes them concurrently under its admission
        controller and pushes progress + per-family results as each
        completes.  Yields the server's JSON events in arrival order;
        ``result`` events gain a decoded ``outputs`` dict (the same arrays
        ``analyze_dir_remote`` returns) in place of the raw payload.

        Event shapes (service/server.py:analyze_dir_stream): ``queued``
        (with the admission queue position), ``admitted``, ``phase``,
        ``result`` (with ``rcache``/``coalesce`` statuses), per-family
        ``error`` (an admission rejection or failure of ONE directory —
        the stream continues), and a terminal ``done``.

        ``watch`` (ISSUE 15) switches the stream to LIVE mode: a dict of
        watch options ({"results_root": <sidecar path>, "max_updates",
        "poll_s", "debounce_s", "figures", "injector"}) attaches this
        stream to a server-side watcher tailing the (single) directory
        mid-sweep; events become ``watching`` / ``report_update`` /
        ``watch_error`` / terminal ``done`` (server _watch_stream
        docstring).  Live-mode streams never restart on UNAVAILABLE once
        events flowed (same replay-safety rule as one-shot)."""
        import base64
        import os

        if isinstance(molly_dirs, str):
            molly_dirs = [molly_dirs]
        req: dict = {"dirs": [os.path.abspath(d) for d in molly_dirs]}
        if corpus_cache is not None:
            req["corpus_cache"] = corpus_cache
        if result_cache is not None:
            req["result_cache"] = result_cache
        if watch is not None:
            req["watch"] = watch
        obs.metrics.inc("rpc.bytes_sent", len(_json.dumps(req).encode("utf-8")))
        md = self._request_metadata()
        # Same shared retry policy as the unary path (ISSUE 9): the JSON
        # request is replayable, so an UNAVAILABLE BEFORE the first event
        # restarts the stream after a jittered wait; mid-stream errors
        # propagate (the consumer already observed events).
        backoff = RPC_POLICY.session()
        while True:
            got_any = False
            try:
                with obs.span(
                    "rpc:AnalyzeDirStream", target=self.target, dirs=len(req["dirs"])
                ):
                    stream = self._analyze_dir_stream(
                        req, timeout=self.timeout, **({"metadata": md} if md else {})
                    )
                    for ev in stream:
                        got_any = True
                        obs.metrics.inc("rpc.stream_events")
                        if ev.get("event") == "result":
                            payload = base64.b64decode(ev.pop("response_b64"))
                            obs.metrics.inc("rpc.bytes_received", len(payload))
                            ev["outputs"] = codec.outputs_from_pb(
                                pb.AnalyzeResponse.FromString(payload)
                            )
                        yield ev
                    _adopt_remote(stream)
                return
            except grpc.RpcError as ex:
                wait = backoff.delay()
                if (
                    got_any
                    or ex.code() != grpc.StatusCode.UNAVAILABLE
                    or wait is None
                ):
                    obs.metrics.inc("rpc.errors")
                    raise
                obs.metrics.inc("rpc.retries")
                obs.metrics.inc("rpc.backoff_s", wait)
                time.sleep(wait)

    def analyze_chunks(
        self, chunks: list[tuple[object, object, dict]]
    ) -> list[dict[str, np.ndarray]]:
        """Stream chunks through the bidi RPC; returns per-chunk outputs in
        submission order (ordinals are verified)."""

        def requests():
            for i, (pre, post, static) in enumerate(chunks):
                req = pb.AnalyzeRequest(
                    pre=codec.batch_arrays_to_pb(pre),
                    post=codec.batch_arrays_to_pb(post),
                    chunk=i,
                )
                req.static.CopyFrom(codec.static_to_pb(static))
                yield req

        # Stream retry rides the same shared policy as the unary RPCs
        # (ISSUE 9 satellite): the request list is replayable, so a
        # CONNECTION-level UNAVAILABLE — nothing received yet — restarts
        # the stream after a jittered wait; once any chunk has landed the
        # error propagates (replaying would double-dispatch server-side).
        backoff = RPC_POLICY.session()
        while True:
            out: list[dict[str, np.ndarray] | None] = [None] * len(chunks)
            try:
                _drive_stream(
                    self._analyze_stream, requests(), self.timeout, self.target, out,
                    **({"extra_md": (("nemo-tenant", self.tenant),)} if self.tenant else {}),
                )
                break
            except grpc.RpcError as ex:
                wait = backoff.delay()
                if (
                    ex.code() != grpc.StatusCode.UNAVAILABLE
                    or any(o is not None for o in out)
                    or wait is None
                ):
                    obs.metrics.inc("rpc.errors")
                    raise
                obs.metrics.inc("rpc.retries")
                obs.metrics.inc("rpc.backoff_s", wait)
                time.sleep(wait)
        missing = [i for i, o in enumerate(out) if o is None]
        if missing:
            raise SidecarError(f"missing responses for chunks {missing}")
        return out  # type: ignore[return-value]


@dataclass
class RemoteExecutor:
    """Drop-in for backend.jax_backend.LocalExecutor that runs every kernel
    on the sidecar: same (verb, arrays, params) contract, carried over the
    Kernel RPC.  Owns its RemoteAnalyzer; close() releases the channel."""

    target: str = "127.0.0.1:50051"
    ready_deadline: float = 30.0

    def __post_init__(self):
        self._client = RemoteAnalyzer(target=self.target)
        try:
            self._client.wait_ready(self.ready_deadline)
        except BaseException:
            # Don't leak the channel (and its worker threads) when the
            # sidecar is unreachable.
            self._client.close()
            raise

    #: Hard wire limit of one Kernel RPC message (server and client channels
    #: both configure grpc.max_*_message_length = 1 GiB, service/server.py).
    MAX_MESSAGE_BYTES = 1 << 30

    def run(
        self, verb: str, arrays: dict, params: dict, rows: int | None = None
    ) -> dict[str, np.ndarray]:
        # `rows` (the caller's real-run count, see LocalExecutor.run) is a
        # metrics/cost hint the wire protocol does not carry; the sidecar's
        # LocalExecutor falls back to the dispatched width, the documented
        # older-client behavior.
        # A single Kernel RPC ships the whole batch in one message each way;
        # bool planes bit-pack 8x on the wire (service/codec.py).  Fail
        # BEFORE serialization with the remedy, not deep inside grpc with
        # RESOURCE_EXHAUSTED — and bound the RESPONSE too: the diff verb's
        # edge_keep readback is a dense [F,V,V] bool plane that dwarfs its
        # own request (F=1024 x V=4096 packs to 2 GiB), and fused/giant
        # return two [B,V,V] clean-adjacency planes.
        def packed_bytes(a) -> int:
            a = np.asarray(a)
            return a.size // 8 if a.dtype == np.bool_ else a.nbytes

        est_req = sum(packed_bytes(v) for v in arrays.values())
        est_resp = 0
        if verb == "diff":
            f = int(np.asarray(arrays["fail_bits"]).shape[0])
            v = int(params["v"])
            est_resp = (f * v * v + 3 * f * v) // 8
        elif verb in ("fused", "giant"):
            b, v = np.asarray(arrays["pre_is_goal"]).shape
            est_resp = 2 * b * v * v // 8 + 8 * b * v
        elif verb == "sparse_fused":
            # The sparse-CSR device step returns contracted EDGE LISTS
            # ([B,E] int32 pairs + masks), never a dense [B,V,V] plane —
            # the upload-narrowing savings compound on the response side.
            b, v = np.asarray(arrays["pre_is_goal"]).shape
            e = int(np.asarray(arrays["pre_edge_src"]).shape[1])
            est_resp = 2 * b * (8 * e + e // 8) + 8 * b * v
        elif verb == "sparse_diff":
            f = int(np.asarray(arrays["fail_bits"]).shape[0])
            v = int(params["v"])
            e = int(np.asarray(arrays["edge_src"]).shape[0])
            est_resp = f * (3 * v + e) // 8
        elif verb == "synth_ext":
            # One [B,T] bool bitset back — the synthesis verb's readback
            # is orders of magnitude below its request.
            b = int(np.asarray(arrays["is_goal"]).shape[0])
            est_resp = b * int(params["num_tables"]) // 8
        est = max(est_req, est_resp)
        if est > self.MAX_MESSAGE_BYTES:
            raise SidecarError(
                f"kernel {verb!r} would move ~{est >> 20} MiB in one message "
                f"(request ~{est_req >> 20}, response ~{est_resp >> 20}), above "
                f"the {self.MAX_MESSAGE_BYTES >> 20} MiB gRPC cap; split the "
                "corpus or use the chunked streaming ingest "
                "(service.client.analyze_dir_pipelined)"
            )
        return self._client.kernel(verb, arrays, params)

    def close(self) -> None:
        self._client.close()


def _stream_pipelined(
    target: str,
    n_chunks: int,
    chunk_iter,
    timings: dict[str, float],
    queue_depth: int = 2,
    ready_deadline: float = 30.0,
    threaded: bool = True,
) -> list[dict[str, np.ndarray]]:
    """Producer/consumer core of the pipelined analysis paths.

    `chunk_iter` yields (i, pre, post, static) packed on demand.  With
    threaded=True a daemon producer thread consumes it into a bounded
    queue, so chunk k+1 packs on the host WHILE chunk k executes on the
    sidecar's device; queue_depth bounds host memory (backpressure).
    threaded=False (the callers' 1-core gate, ISSUE 3 satellite) skips the
    thread entirely: the gRPC request generator pulls each chunk lazily
    from the iterator, so packing serializes with the stream — on one
    effective core the thread cannot overlap anyway, and the GIL handoffs
    and queue traffic are pure overhead — while the bounded-memory
    contract still holds (at most one packed chunk in flight).

    Failure contract (ADVICE r2): if the stream dies mid-flight, the abort
    event is set and the queue drained so the producer can never block
    forever in a full queue (leaking the thread and packed batches), and a
    producer/packing exception is re-raised chained (not swallowed into a
    generic RpcError) on either path.
    """
    import queue
    import threading

    prod_exc: list[BaseException] = []
    results: list[dict[str, np.ndarray] | None] = [None] * n_chunks

    def _request_of(item):
        i, pre, post, static = item
        req = pb.AnalyzeRequest(
            pre=codec.batch_arrays_to_pb(pre),
            post=codec.batch_arrays_to_pb(post),
            chunk=i,
        )
        req.static.CopyFrom(codec.static_to_pb(static))
        return req

    def _finish() -> list[dict[str, np.ndarray]]:
        if prod_exc:
            # The stream itself completed, but the producer still failed
            # (e.g. after its last emitted chunk was consumed).  Don't drop
            # it: a clean-looking result from a failed producer is a
            # silent-corruption hazard (ADVICE r3 #2).
            raise SidecarError(
                f"producer failed after streaming completed: {prod_exc[0]!r}"
            ) from prod_exc[0]
        missing = [i for i, o in enumerate(results) if o is None]
        if missing:
            raise SidecarError(f"missing responses for chunks {missing}")
        return results  # type: ignore[return-value]

    if not threaded:

        def requests_inline():
            try:
                for item in chunk_iter:
                    yield _request_of(item)
            except BaseException as ex:  # surfaced after the stream ends
                prod_exc.append(ex)
                return

        try:
            with RemoteAnalyzer(target=target) as client:
                client.wait_ready(ready_deadline)
                t0 = time.perf_counter()
                _drive_stream(
                    client._analyze_stream, requests_inline(), client.timeout,
                    target, results,
                    **(
                        {"extra_md": (("nemo-tenant", t),)}
                        if (t := getattr(client, "tenant", None))
                        else {}
                    ),
                )
                timings["stream_s"] = time.perf_counter() - t0
        except BaseException as ex:
            if prod_exc:
                raise SidecarError(
                    f"producer failed while streaming: {prod_exc[0]!r}"
                ) from prod_exc[0]
            raise ex
        return _finish()

    q: queue.Queue = queue.Queue(maxsize=max(1, queue_depth))
    abort = threading.Event()
    _END = object()

    def emit(item) -> bool:
        while not abort.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in chunk_iter:
                if not emit(item):
                    return
        except BaseException as ex:  # surface in the consumer
            prod_exc.append(ex)
            emit(ex)
        finally:
            emit(_END)

    thread = threading.Thread(target=producer, daemon=True, name="nemo-pack")
    thread.start()

    def requests():
        while True:
            item = q.get()
            if item is _END or abort.is_set():
                return
            if isinstance(item, BaseException):
                raise item
            yield _request_of(item)

    try:
        with RemoteAnalyzer(target=target) as client:
            client.wait_ready(ready_deadline)
            t0 = time.perf_counter()
            _drive_stream(
                client._analyze_stream, requests(), client.timeout, target, results,
                **(
                        {"extra_md": (("nemo-tenant", t),)}
                        if (t := getattr(client, "tenant", None))
                        else {}
                    ),
            )
            timings["stream_s"] = time.perf_counter() - t0
    except BaseException as ex:
        if prod_exc:
            raise SidecarError(
                f"producer failed while streaming: {prod_exc[0]!r}"
            ) from prod_exc[0]
        raise ex
    finally:
        abort.set()
        # Unblock a producer stuck in q.put, then guarantee the sentinel is
        # IN the queue: grpc's request-consumer thread may be blocked in the
        # untimed q.get() inside requests(), and after abort the producer's
        # own emit(_END) no-ops — without this re-put that thread would leak.
        while True:
            try:
                q.put_nowait(_END)
                break
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    continue
        thread.join(timeout=5.0)
    if thread.is_alive():
        # The join timed out with the producer still running: its exception
        # state is unknowable, so a clean-looking result can't be trusted
        # (the daemon thread could raise right after we return).
        raise SidecarError(
            "producer thread still running after streaming completed "
            "(join timed out); result discarded as unverifiable"
        )
    return _finish()


def analyze_dirs(
    target: str, molly_dirs: list[str], queue_depth: int = 2
) -> tuple[list[dict[str, np.ndarray]], dict[str, float]]:
    """Pipelined multi-corpus analysis with TRUE ingest/compute overlap
    (SURVEY.md §2.3 pipeline-parallel row; VERDICT r1 item 5).

    A producer thread packs each sweep directory through the injector seam
    (ingest/adapters.py — natively when the adapter's layout supports it)
    and feeds a bounded queue; the bidi AnalyzeStream RPC consumes from the
    queue, so directory k+1 is parsing/packing on the host WHILE directory
    k executes on the sidecar's device.  queue_depth bounds host memory
    (backpressure).  On a 1-core host the producer thread is skipped
    (pack inline, then stream — utils.effective_cpu_count) and the timing
    dict says so.  Returns (per-directory outputs, timing dict with
    pack_s, stream_s, wall_s, overlap — overlap win = pack_s + stream_s -
    wall_s when overlap is True and the win is positive).
    """
    from nemo_tpu.utils import effective_cpu_count

    t_wall0 = time.perf_counter()
    overlap = effective_cpu_count() > 1
    timings = {"pack_s": 0.0, "stream_s": 0.0, "wall_s": 0.0, "overlap": overlap}

    def chunks():
        from nemo_tpu.ingest.adapters import resolve_injector

        for i, d in enumerate(molly_dirs):
            t0 = time.perf_counter()
            with obs.span("pack:dir", ordinal=i):
                pre, post, static = resolve_injector(d).pack_steps(d)
            timings["pack_s"] += time.perf_counter() - t0
            yield (i, pre, post, static)

    results = _stream_pipelined(
        target, len(molly_dirs), chunks(), timings, queue_depth, threaded=overlap
    )
    timings["wall_s"] = time.perf_counter() - t_wall0
    return results, timings


def _uniform_spans(n: int, chunk_runs: int) -> tuple[list[tuple[int, int]], int]:
    """(spans, pad_to): corpus row spans sized so every chunk DISPATCH has
    exactly pad_to rows — chunk 0 is rows [0, chunk_runs) (row 0 is the
    corpus baseline), later chunks carry the prepended baseline plus
    chunk_runs-1 fresh rows, and short tails pad with baseline copies
    (_chunk_rows pad_to).  Uniform shapes mean the sidecar compiles ONE
    program per corpus bucket signature for the whole stream — per-chunk
    shapes were costing a fresh jit compile (~10s on the TPU tunnel) per
    distinct batch size, which dwarfed the overlap win chunking exists for.

    pad_to is 0 (no padding) when nothing is gained by it: a single-span
    corpus keeps its natural b (the same shape the unchunked deployment
    dispatch compiles), and chunk_runs==1 has no room for the baseline
    prepend (size-1 spans dispatch at b=1 then b=2, as before)."""
    if n <= chunk_runs:
        return [(0, n)], 0
    if chunk_runs <= 1:
        return [(s, s + 1) for s in range(n)], 0
    spans = [(0, chunk_runs)]
    s = chunk_runs
    while s < n:
        spans.append((s, min(s + chunk_runs - 1, n)))
        s = spans[-1][1]
    return spans, chunk_runs


def _chunk_rows(batch_like, s: int, e: int, with_baseline: bool, pad_to: int = 0):
    """Rows [s:e) of a batch (BatchArrays OR the native corpus's host-side
    cond batch — anything exposing the 8 packed fields) as host-numpy
    BatchArrays, optionally with the corpus baseline run (row 0 — the row
    the fused step diffs against) prepended, then padded to pad_to rows
    with baseline copies (pad rows are the good run diffed against itself;
    _merge_chunk_outputs drops them).  The SINGLE chunk-slicing
    implementation for analyze_dir's chunked path and the pipelined
    producer, so the baseline-prepend semantics can never diverge; always
    numpy so chunk payloads never bounce through the device before protobuf
    serialization."""
    from nemo_tpu.models.pipeline_model import BatchArrays

    def cut(x):
        x = np.asarray(x)
        out = np.concatenate([x[:1], x[s:e]]) if with_baseline else x[s:e]
        if pad_to and out.shape[0] < pad_to:
            pad = np.repeat(x[:1], pad_to - out.shape[0], axis=0)
            out = np.concatenate([out, pad])
        return out

    return BatchArrays(
        **{f: cut(getattr(batch_like, f)) for f in BatchArrays.FIELDS}
    )


def _merge_chunk_outputs(
    spans: list[tuple[int, int]],
    results: list[dict[str, np.ndarray]],
    pad_to: int = 0,
) -> dict[str, np.ndarray]:
    """Merge per-chunk fused-step outputs into the unchunked equivalent.

    pad_to nonzero means every chunk was dispatched at exactly pad_to rows
    (_uniform_spans/_chunk_rows) — tail baseline-copy pad rows are dropped
    before concatenation.

    Per-run rows: pad trailing dims up to the widest chunk's (the corpus
    vocab is append-only, so an earlier chunk's table/label columns are a
    prefix of a later one's; absent columns pad False/0, and
    proto_min_depth pads DEPTH_INF = "table absent"), drop the prepended
    good row of chunks > 0, concatenate.

    Cross-run reductions (proto_inter/proto_union) are recomputed exactly
    from the merged per-run proto_bits + achieved_pre with reduce_protos
    semantics (ops/proto.py:81-87) — NOT by AND/OR-ing the chunks' own
    reductions, which would require every chunk to contain an achieving run
    and would crash on width-mismatched 1-D outputs.
    """
    from nemo_tpu.models.pipeline_model import CORPUS_REDUCTIONS
    from nemo_tpu.ops.proto import DEPTH_INF

    # Every registered reduction key needs an explicit recompute rule below;
    # silently dropping one (or AND/OR-ing chunk reductions, which is wrong
    # when a chunk has no achieving run) must fail loudly instead.
    unmerged = set(CORPUS_REDUCTIONS) & set(results[0]) - {"proto_inter", "proto_union"}
    if unmerged:
        raise SidecarError(
            f"no chunk-merge rule for reduction outputs {sorted(unmerged)}; "
            "add a recompute in _merge_chunk_outputs"
        )
    pad_value = {"proto_min_depth": DEPTH_INF}
    merged: dict[str, np.ndarray] = {}
    for key in results[0]:
        if key in CORPUS_REDUCTIONS:
            continue  # recomputed from per-run outputs below
        arrs = [r[key] for r in results]
        trailing = tuple(
            max(a.shape[d] for a in arrs) for d in range(1, arrs[0].ndim)
        )
        padded = []
        for a in arrs:
            if a.shape[1:] != trailing:
                wide = np.full(
                    (a.shape[0],) + trailing, pad_value.get(key, 0), dtype=a.dtype
                )
                wide[tuple(slice(0, s) for s in a.shape)] = a
                a = wide
            padded.append(a)
        rows = []
        for (s, e), r in zip(spans, padded):
            real = (e - s) + (1 if s > 0 else 0)
            expected = pad_to if pad_to else real
            if r.shape[0] != expected:
                raise SidecarError(
                    f"output {key!r} is not per-run shaped "
                    f"(got leading dim {r.shape[0]}, batch {expected}); "
                    "register it in models.pipeline_model.CORPUS_REDUCTIONS"
                )
            # Drop tail pad rows (baseline copies), then the prepended
            # baseline of chunks > 0.
            rows.append(r[1:real] if s > 0 else r[:real])
        merged[key] = np.concatenate(rows, axis=0)

    bits = merged["proto_bits"].astype(bool)
    ach = merged["achieved_pre"].astype(bool)
    masked = bits & ach[:, None]
    merged["proto_inter"] = np.all(masked | ~ach[:, None], axis=0) & ach.any()
    merged["proto_union"] = np.any(masked, axis=0)
    return merged


def analyze_dir(target: str, molly_dir: str, chunk_runs: int = 0) -> dict[str, np.ndarray]:
    """Pack a sweep directory through the injector seam
    (ingest/adapters.py — Molly gets the native packed-first ETL,
    trace-JSON the adapter load + Python pack) and analyze it remotely,
    optionally streamed in chunks of chunk_runs runs.

    Chunked results are merged to be equivalent to one unchunked call: every
    chunk gets the corpus's good run (row 0) prepended so the differential
    provenance baseline (analysis_step diffs against its batch's row 0) and
    the prototype reductions see it; the duplicate row is dropped from
    per-run outputs and the cross-chunk reductions are re-combined.
    """
    from nemo_tpu.ingest.adapters import resolve_injector

    pre, post, static = resolve_injector(molly_dir).pack_steps(molly_dir)
    b = int(np.asarray(pre.is_goal).shape[0])
    with RemoteAnalyzer(target=target) as client:
        client.wait_ready()
        if not chunk_runs or chunk_runs >= b:
            return client.analyze(pre, post, static)

        spans, pad_to = _uniform_spans(b, chunk_runs)
        chunks = [
            (
                _chunk_rows(pre, s, e, with_baseline=s > 0, pad_to=pad_to),
                _chunk_rows(post, s, e, with_baseline=s > 0, pad_to=pad_to),
                static,
            )
            for s, e in spans
        ]
        results = client.analyze_chunks(chunks)

    return _merge_chunk_outputs(spans, results, pad_to=pad_to)


def analyze_dir_pipelined(
    target: str, molly_dir: str, chunk_runs: int = 512, queue_depth: int = 2
) -> tuple[dict[str, np.ndarray], dict[str, float]]:
    """Single-directory analysis with ingest/compute overlap (VERDICT r2
    item 8): one big Molly family is parsed + packed in CHUNKS of
    chunk_runs by the producer thread, so chunk k+1's JSON parse/pack
    overlaps chunk k's device execution — the same pipeline shape
    analyze_dirs gives across directories, inside one directory.

    Chunk semantics match analyze_dir's chunked path: every chunk after the
    first gets the corpus's baseline run (file position 0 — the batch row
    the fused step diffs against, matching the unchunked dispatch)
    prepended.  Chunks pack against the shared, append-only corpus vocab,
    so later chunks may have wider table/label dims and bigger node
    buckets; _merge_chunk_outputs pads and recombines them into the exact
    unchunked result.

    Returns (merged outputs, timings with pack_s / stream_s / wall_s /
    overlap — overlap win = pack_s + stream_s - wall_s when overlap is
    True and the win is positive; overlap=False means the 1-core gate
    packed inline and no win should be derived)."""
    import json
    import os

    from nemo_tpu.graphs.packed import CorpusVocab, pack_graph
    from nemo_tpu.ingest.adapters import MollyInjector, resolve_injector
    from nemo_tpu.ingest.datatypes import RunData
    from nemo_tpu.ingest.molly import load_run_prov
    from nemo_tpu.models.pipeline_model import graphs_to_step

    from nemo_tpu.utils import effective_cpu_count

    t_wall0 = time.perf_counter()
    # 1-core gate (ISSUE 3 satellite): with no second core the producer
    # thread cannot overlap the stream — pack inline, stream after, and
    # record overlap=False so the bench row reports the machinery as
    # disabled instead of shipping a negative overlap win.
    overlap = effective_cpu_count() > 1
    timings = {"pack_s": 0.0, "stream_s": 0.0, "wall_s": 0.0, "overlap": overlap}

    injector = resolve_injector(molly_dir)
    n = injector.count_runs(molly_dir)
    if n == 0:
        raise SidecarError(
            f"no runs in {molly_dir} (empty {type(injector).index_file})"
        )
    chunk_runs = max(1, chunk_runs)
    spans, pad_to = _uniform_spans(n, chunk_runs)

    from nemo_tpu.ingest.native import packed_host_available

    if type(injector).native_capable and packed_host_available(molly_dir):
        # Packed-first producer: ONE C++ parse of the whole directory (~6x
        # the Python per-chunk parser's throughput) — or, on any host, ONE
        # mmap load from a warm corpus store — then chunks are plain
        # HOST row slices of the corpus arrays (_chunk_rows — never through
        # the device; the wire wants host bytes anyway).  All chunks share
        # the corpus-wide vocab and bucket AND a uniform batch size
        # (_uniform_spans), so the sidecar compiles exactly one program
        # for the whole stream.
        from nemo_tpu.ingest.native import pack_molly_dir_host

        t0 = time.perf_counter()
        corpus, static = pack_molly_dir_host(molly_dir)
        if corpus.n_runs != n:
            raise SidecarError(
                f"native corpus has {corpus.n_runs} runs but runs.json has {n}"
            )
        timings["pack_s"] += time.perf_counter() - t0

        def chunks():
            for ci, (s, e) in enumerate(spans):
                t0 = time.perf_counter()
                with obs.span("pack:chunk", chunk=ci):
                    chunk = (
                        ci,
                        _chunk_rows(corpus.pre, s, e, with_baseline=ci > 0, pad_to=pad_to),
                        _chunk_rows(corpus.post, s, e, with_baseline=ci > 0, pad_to=pad_to),
                        static,
                    )
                timings["pack_s"] += time.perf_counter() - t0
                yield chunk

    elif isinstance(injector, MollyInjector):
        # Lib-less Molly host: the layout is one file per run, so the
        # producer parses + packs incrementally — chunk k+1's JSON work
        # genuinely overlaps chunk k's device execution.
        with open(os.path.join(molly_dir, "runs.json"), "r", encoding="utf-8") as f:
            raw_runs = json.load(f)
        vocab = CorpusVocab()
        good: dict = {}  # filled by chunk 0: {"rid", "pre", "post"}

        def chunks():
            for ci, (s, e) in enumerate(spans):
                t0 = time.perf_counter()
                rids, pres, posts = [], [], []
                if ci > 0:
                    rids.append(good["rid"])
                    pres.append(good["pre"])
                    posts.append(good["post"])
                for pos in range(s, e):
                    run = RunData.from_json(raw_runs[pos])
                    load_run_prov(molly_dir, pos, run)
                    rids.append(run.iteration)
                    pres.append(pack_graph(run.pre_prov, vocab))
                    posts.append(pack_graph(run.post_prov, vocab))
                if ci == 0:
                    good.update(rid=rids[0], pre=pres[0], post=posts[0])
                while pad_to and len(rids) < pad_to:
                    # Tail pad with baseline copies so the dispatch batch
                    # size stays uniform (dropped by _merge_chunk_outputs).
                    rids.append(good["rid"])
                    pres.append(good["pre"])
                    posts.append(good["post"])
                pre_b, post_b, static = graphs_to_step(rids, pres, posts, vocab)
                timings["pack_s"] += time.perf_counter() - t0
                yield (ci, pre_b, post_b, static)

    else:
        # Generic injector (e.g. trace-json): a single-document layout
        # has no per-run file boundary to parse incrementally, so the
        # producer packs the whole sweep ONCE through the seam and chunks
        # are host row slices — analyze_dir's chunk shape, streamed.  The
        # slices (and any later chunks' wider-vocab merges) still overlap
        # the device stream; only the initial pack is serial.
        def chunks():
            t0 = time.perf_counter()
            with obs.span("pack:corpus"):
                pre_b, post_b, static = injector.pack_steps(molly_dir)
            timings["pack_s"] += time.perf_counter() - t0
            for ci, (s, e) in enumerate(spans):
                t0 = time.perf_counter()
                with obs.span("pack:chunk", chunk=ci):
                    chunk = (
                        ci,
                        _chunk_rows(pre_b, s, e, with_baseline=ci > 0, pad_to=pad_to),
                        _chunk_rows(post_b, s, e, with_baseline=ci > 0, pad_to=pad_to),
                        static,
                    )
                timings["pack_s"] += time.perf_counter() - t0
                yield chunk

    results = _stream_pipelined(
        target, len(spans), chunks(), timings, queue_depth, threaded=overlap
    )
    merged = _merge_chunk_outputs(spans, results, pad_to=pad_to)
    timings["wall_s"] = time.perf_counter() - t_wall0
    return merged, timings
