"""The TPU sidecar: a gRPC service running the fused analysis step on device.

Architecture per SURVEY.md §7: the CLI/ETL process packs provenance into
integer arrays (natively, ingest/native.py) and streams them here; this
process owns the accelerator, jits the fused pipeline once per
(shapes, statics) signature, and streams results back.  Replaces the
reference's per-node/edge Bolt round-trips to Neo4j (SURVEY.md §3.1 hot
loop #1) with one RPC per chunk of thousands of runs.

grpcio is present in this environment but its codegen plugin is not, so the
service is registered through grpc's generic-handler API with the
protoc-generated message classes doing (de)serialization.

Run:  python -m nemo_tpu.service.server --port 50051
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
from concurrent import futures

import grpc

from nemo_tpu import obs
from nemo_tpu.obs import trace as obs_trace
from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb

SERVICE = "nemo.NemoAnalysis"
VERSION = "1"

log = logging.getLogger("nemo.sidecar")


#: Traced requests sharing the lazily-created PATHLESS collector tracer.
#: When the count returns to zero the collector is torn down, so a
#: long-lived sidecar serving untraced traffic records no spans at all —
#: the collector exists only while a traced request is in flight.
_collector_lock = threading.Lock()
_collector_refs = [0]


class _SpanCollection:
    """Per-request span-collection state.

    A tracing client sends its trace id in 'nemo-trace-id' request
    metadata; the handler records its spans under that id and returns them
    in 'nemo-spans-bin' trailing metadata, which the client stitches into
    its own trace file — one Perfetto view, both processes.  Collection is
    best-effort: with several concurrently tracing clients, spans may ride
    home on the wrong response (they still belong to the same sidecar
    timeline); the metrics counters are exact regardless.

    Lifecycle: acquire on construction (lazily enabling a pathless
    collector tracer unless the operator set NEMO_TRACE — an operator's
    file tracer is only copied from, never drained), serialize with
    trailing(), and ALWAYS release() (handlers do it in a finally) so the
    pathless collector is torn down when the last traced request finishes.
    """

    #: One response's span payload cap.  gRPC refuses oversized metadata
    #: (make_server/RemoteAnalyzer raise grpc.max_metadata_size above
    #: this); a huge streamed corpus keeps its NEWEST spans.
    MAX_BYTES = 1 << 20

    def __init__(self, context) -> None:
        md = dict(context.invocation_metadata() or ())
        self.tid = md.get("nemo-trace-id")
        self._owned = False
        self._tracer = None
        self._mark = 0
        if self.tid is None:
            return
        with _collector_lock:
            t = obs.tracer()
            if t is None:
                t = obs_trace.start_trace(None)
            if not t.path:
                _collector_refs[0] += 1
                self._owned = True
            self._tracer = t
            self._mark = t.mark()

    def trailing(self) -> tuple:
        """Trailing-metadata entries carrying the spans this request
        recorded (capped at MAX_BYTES, oldest dropped first)."""
        t = self._tracer
        if t is None:
            return ()
        spans = t.spans_since(self._mark) if t.path else t.drain_spans()
        payload = b""
        while spans:
            payload = json.dumps(spans).encode("utf-8")
            if len(payload) <= self.MAX_BYTES:
                break
            # Keep the newest spans: for a streamed corpus they cover the
            # most recent chunks, and the client's own rpc span still
            # brackets the whole call.
            spans = spans[max(1, len(spans) // 4):]
        if not spans or len(payload) > self.MAX_BYTES:
            return ()
        return (("nemo-spans-bin", payload),)

    def release(self) -> None:
        if not self._owned:
            return
        self._owned = False
        with _collector_lock:
            _collector_refs[0] -= 1
            t = obs.tracer()
            if _collector_refs[0] == 0 and t is not None and not t.path:
                # finish() on a pathless tracer writes nothing — it just
                # disables collection until the next traced request.
                obs_trace.finish()


class _Impl:
    """Method implementations; one fused-step jit cache per process.

    Trace-context propagation is per request via _SpanCollection; every
    handler acquires one and releases it in a finally.
    """

    def health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        col = _SpanCollection(context)
        try:
            with obs.span("serve:Health", trace_id=col.tid):
                import jax

                devs = jax.devices()
                resp = pb.HealthResponse(
                    platform=devs[0].platform, device_count=len(devs), version=VERSION
                )
            # The metrics snapshot rides every Health response (trailing
            # metadata — no proto bump): operators inspect sidecar state
            # (dispatch counts, compile-cache hits, step latencies) through
            # any client's health() without SSH.
            context.set_trailing_metadata(
                (("nemo-metrics-bin", json.dumps(obs.metrics.snapshot()).encode("utf-8")),)
                + col.trailing()
            )
            return resp
        finally:
            col.release()

    def _analyze_one(
        self, request: pb.AnalyzeRequest, trace_id: str | None = None
    ) -> pb.AnalyzeResponse:
        import jax

        from nemo_tpu.models.pipeline_model import analysis_step

        from nemo_tpu.backend.jax_backend import _pack_out_default, _unpack_summary

        pre = codec.batch_arrays_from_pb(request.pre)
        post = codec.batch_arrays_from_pb(request.post)
        static = codec.static_from_pb(request.static)
        b = int(pre.is_goal.shape[0])
        t0 = time.perf_counter()
        # The server owns the device, so it decides the transfer folding
        # (like LocalExecutor.run): with pack_out the program's bool
        # outputs — including this path's diff tail — arrive as ONE
        # bit-packed device->host copy and unpack here, before the wire
        # codec (which bit-packs bools again for transport).  Clients are
        # unaffected; this static never comes from the request.
        static = dict(static, pack_out=bool(_pack_out_default()))
        with obs.span(
            "serve:analysis_step", chunk=int(request.chunk), rows=b, trace_id=trace_id
        ):
            out = analysis_step(pre, post, **static)
            out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        obs.metrics.inc("serve.analyze_chunks")
        obs.metrics.observe("serve.step_s", dt)
        obs.metrics.observe("serve.batch_rows", b)
        if "packed_summary" in out:
            out = dict(out)
            out.update(
                _unpack_summary(
                    out.pop("packed_summary"),
                    b=int(pre.is_goal.shape[0]),
                    v=int(static["v"]),
                    t=int(static["num_tables"]),
                    # Derive from the same dict used for dispatch so the
                    # packed layout and the unpack can never diverge if the
                    # codec ever starts carrying with_diff (ADVICE r4 #2).
                    with_diff=bool(static.get("with_diff", True)),
                )
            )
        return codec.outputs_to_pb(out, chunk=request.chunk, step_seconds=dt)

    def analyze(self, request: pb.AnalyzeRequest, context) -> pb.AnalyzeResponse:
        col = _SpanCollection(context)
        try:
            resp = self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return resp
        finally:
            col.release()

    def analyze_stream(self, request_iterator, context):
        # Sequential device dispatch preserves chunk arrival order; gRPC's
        # flow control provides the backpressure (SURVEY.md §7 hard part 6).
        col = _SpanCollection(context)
        try:
            for request in request_iterator:
                yield self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
        finally:
            col.release()

    def kernel(self, request: pb.KernelRequest, context) -> pb.KernelResponse:
        """Named device-kernel dispatch for the ServiceBackend: the request's
        (verb, arrays, params) triple runs through the same LocalExecutor the
        in-process JaxBackend uses, so both deployments execute identical
        device code."""
        from nemo_tpu.backend.jax_backend import LocalExecutor

        col = _SpanCollection(context)
        try:
            verb, arrays, params = codec.kernel_request_from_pb(request)
            if verb not in LocalExecutor.VERBS:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel verb {verb!r}")
            t0 = time.perf_counter()
            try:
                # LocalExecutor is stateless; the jit caches live on the
                # module-level kernel functions.  Its own kernel:<verb> span
                # rides home in the trailing metadata.
                with obs.span("serve:Kernel", verb=verb, trace_id=col.tid):
                    out = LocalExecutor().run(verb, arrays, params)
            except KeyError as ex:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"missing kernel input: {ex}")
            obs.metrics.inc("serve.kernel_calls")
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return codec.kernel_response_to_pb(out, step_seconds=time.perf_counter() - t0)
        finally:
            col.release()


def make_server(port: int = 0, max_workers: int = 4) -> tuple[grpc.Server, int]:
    """Build (but don't start) the sidecar server; returns (server, port)."""
    impl = _Impl()
    handlers = {
        "Health": grpc.unary_unary_rpc_method_handler(
            impl.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
        "Analyze": grpc.unary_unary_rpc_method_handler(
            impl.analyze,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "AnalyzeStream": grpc.stream_stream_rpc_method_handler(
            impl.analyze_stream,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "Kernel": grpc.unary_unary_rpc_method_handler(
            impl.kernel,
            request_deserializer=pb.KernelRequest.FromString,
            response_serializer=pb.KernelResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 1 << 30),
            ("grpc.max_send_message_length", 1 << 30),
            # Span trailing metadata (traced clients) can reach
            # _SpanCollection.MAX_BYTES; default metadata limits are 8 KB.
            ("grpc.max_metadata_size", 2 << 20),
        ],
    )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nemo-tpu-sidecar")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument("--max-workers", type=int, default=4)
    parser.add_argument(
        "--profiler-port",
        type=int,
        default=0,
        help="start jax.profiler.start_server on this port so TensorBoard/"
        "xprof can capture device traces from the running sidecar (0 = off)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe under a watchdog, CPU fallback on "
        "tunnel outage), 'cpu', 'tpu', or a concrete platform name "
        "(default: $NEMO_PLATFORM or auto)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from nemo_tpu.utils.jax_config import (
        PlatformUnavailableError,
        enable_compilation_cache,
        ensure_platform,
    )

    # The sidecar owns the accelerator; resolve the platform under a
    # watchdog so a tunnel outage degrades to a CPU sidecar (loudly) instead
    # of a server whose first RPC hangs forever (VERDICT r2 weak #3).  An
    # explicit --platform=tpu demand with no reachable device refuses to
    # start at all rather than serving CPU answers under a TPU flag.
    try:
        platform = ensure_platform(args.platform, log=log.warning)
    except PlatformUnavailableError as e:
        log.error("fatal: %s", e)
        return 2
    log.info("jax platform: %s", platform)
    enable_compilation_cache()
    # NEMO_TRACE=<file> makes the sidecar write its OWN Perfetto trace at
    # shutdown; traced clients additionally collect per-RPC spans in-band
    # either way (obs/trace.py).
    if obs_trace.configure_from_env() is not None:
        log.info("obs tracing -> %s", obs.tracer().path)
    if args.profiler_port:
        import jax

        jax.profiler.start_server(args.profiler_port)
        log.info("jax profiler server on port %d", args.profiler_port)
    server, port = make_server(args.port, args.max_workers)
    server.start()
    log.info("sidecar listening on 127.0.0.1:%d", port)
    server.wait_for_termination()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
