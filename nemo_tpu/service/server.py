"""The TPU sidecar: a gRPC service running the fused analysis step on device.

Architecture per SURVEY.md §7: the CLI/ETL process packs provenance into
integer arrays (natively, ingest/native.py) and streams them here; this
process owns the accelerator, jits the fused pipeline once per
(shapes, statics) signature, and streams results back.  Replaces the
reference's per-node/edge Bolt round-trips to Neo4j (SURVEY.md §3.1 hot
loop #1) with one RPC per chunk of thousands of runs.

grpcio is present in this environment but its codegen plugin is not, so the
service is registered through grpc's generic-handler API with the
protoc-generated message classes doing (de)serialization.

Operational surface (ISSUE 4): `--metrics-port` / `NEMO_METRICS_PORT`
serves the obs metrics registry in Prometheus text format on a stdlib
http.server thread (`/metrics`, plus `/healthz` mirroring the gRPC Health
state) so a long-lived sidecar is scrapeable; every log line is a
structured JSON record (obs/log.py) carrying the client's propagated trace
id where one exists, and every RPC lands in a `serve.rpc_latency_s.<rpc>`
histogram.

Serving tier (ISSUE 8, nemo_tpu/serve): every work RPC (Analyze,
AnalyzeStream, AnalyzeDir, AnalyzeDirStream, Kernel — Health stays
ungated) passes the admission controller first: a bounded queue with
per-tenant round-robin fairness (`nemo-tenant` request metadata), an
in-flight cap (`--max-inflight`/`NEMO_SERVE_INFLIGHT`), and
RESOURCE_EXHAUSTED rejection carrying a `nemo-retry-after-s` hint when the
queue is full.  Concurrent AnalyzeDir requests with the same content
address (store segment fingerprints + statics + wire/ABI versions — the
rcache tier-3 key) coalesce into ONE analysis with byte-identical
responses; compatible Kernel dispatches from different in-flight requests
merge into one padded device launch (continuous batching).
`AnalyzeDirStream` streams per-directory results and queue/phase progress
events as each completes.  SIGTERM drains gracefully: new admissions are
refused (`/healthz` -> NOT_SERVING), in-flight requests finish inside
`NEMO_SERVE_DRAIN_S`, then the process exits 0.

Fleet (ISSUE 14): `--shared-cache DIR` / `NEMO_RCACHE_SHARED` attaches
this replica to the fleet's shared result-cache tier — any replica serves
any warm corpus, publishes replicate, and a cold herd's concurrent
identical requests across REPLICAS coalesce through a leader lease in the
shared tier (one analysis fleet-wide, `nemo-fleet` trailing status).
`--prewarm` warms the bucket-signature programs at boot so scale-out adds
capacity in seconds.  `--router --backends h:p,...` turns the process
into the thin consistent-hash router instead (nemo_tpu/serve/router.py).

Run:  python -m nemo_tpu.service.server --port 50051 --metrics-port 9464
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from concurrent import futures

import grpc

from nemo_tpu import obs
from nemo_tpu import serve
from nemo_tpu.obs import log as obs_log
from nemo_tpu.obs import trace as obs_trace
from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb

SERVICE = "nemo.NemoAnalysis"
VERSION = "1"

log = obs_log.get_logger("nemo.sidecar")


def _replica_id() -> str:
    """This replica's fleet identity (lease ownership, log attribution)."""
    import socket as _socket

    return f"{_socket.gethostname()}-{os.getpid()}"


def _health_state() -> dict:
    """The `/healthz` document: a JSON mirror of the gRPC Health response
    (same fields a `health()` client sees), computed per request so an
    operator's curl reflects live device state.  A draining sidecar
    (SIGTERM received, in-flight work finishing) reports NOT_SERVING —
    promexp answers it with a 503, which is what pulls a replica out of a
    load balancer's rotation before the process exits."""
    import jax

    ctl = serve.controller()
    devs = jax.devices()
    return {
        "status": "NOT_SERVING" if ctl.draining else "SERVING",
        "platform": devs[0].platform,
        "device_count": len(devs),
        "version": VERSION,
        "inflight": ctl.inflight,
        "queue_depth": ctl.queued,
    }


def _tenant_of(context) -> str:
    """The caller's tenant identity from 'nemo-tenant' request metadata
    (sanitized; absent -> the shared 'anon' tenant)."""
    md = dict(context.invocation_metadata() or ())
    return serve.admission.sanitize_tenant(md.get("nemo-tenant"))


def _rpc_observed(name: str, t0: float, trace_id: str | None) -> None:
    """Per-RPC server-side accounting shared by every handler: the latency
    histogram the Prometheus endpoint exposes, plus a trace-correlated
    debug record (the log line that joins a scrape, a trace file, and a
    client's story under one id)."""
    dt = time.perf_counter() - t0
    obs.metrics.observe(f"serve.rpc_latency_s.{name}", dt)
    log.debug(
        "serve.rpc", rpc=name, seconds=round(dt, 6),
        trace_id=trace_id,
    )
    slow_ms = obs_log.slow_dispatch_ms()
    if slow_ms and dt * 1000.0 > slow_ms:
        obs.metrics.inc("watchdog.slow_rpc")
        log.warning(
            "serve.slow_rpc", rpc=name, wall_ms=round(dt * 1000.0, 1),
            threshold_ms=slow_ms, trace_id=trace_id,
        )


#: Traced requests sharing the lazily-created PATHLESS collector tracer.
#: When the count returns to zero the collector is torn down, so a
#: long-lived sidecar serving untraced traffic records no spans at all —
#: the collector exists only while a traced request is in flight.
_collector_lock = threading.Lock()
_collector_refs = [0]


class _SpanCollection:
    """Per-request span-collection state.

    A tracing client sends its trace id in 'nemo-trace-id' request
    metadata; the handler records its spans under that id and returns them
    in 'nemo-spans-bin' trailing metadata, which the client stitches into
    its own trace file — one Perfetto view, both processes.  Collection is
    best-effort: with several concurrently tracing clients, spans may ride
    home on the wrong response (they still belong to the same sidecar
    timeline); the metrics counters are exact regardless.

    Lifecycle: acquire on construction (lazily enabling a pathless
    collector tracer unless the operator set NEMO_TRACE — an operator's
    file tracer is only copied from, never drained), serialize with
    trailing(), and ALWAYS release() (handlers do it in a finally) so the
    pathless collector is torn down when the last traced request finishes.
    """

    #: One response's span payload cap.  gRPC refuses oversized metadata
    #: (make_server/RemoteAnalyzer raise grpc.max_metadata_size above
    #: this); a huge streamed corpus keeps its NEWEST spans.
    MAX_BYTES = 1 << 20

    def __init__(self, context) -> None:
        md = dict(context.invocation_metadata() or ())
        self.tid = md.get("nemo-trace-id")
        self._owned = False
        self._tracer = None
        self._mark = 0
        if self.tid is None:
            return
        with _collector_lock:
            t = obs.tracer()
            if t is None:
                t = obs_trace.start_trace(None)
            if not t.path:
                _collector_refs[0] += 1
                self._owned = True
            self._tracer = t
            self._mark = t.mark()

    def trailing(self) -> tuple:
        """Trailing-metadata entries carrying the spans this request
        recorded (capped at MAX_BYTES, oldest dropped first)."""
        t = self._tracer
        if t is None:
            return ()
        spans = t.spans_since(self._mark) if t.path else t.drain_spans()
        payload = b""
        while spans:
            payload = json.dumps(spans).encode("utf-8")
            if len(payload) <= self.MAX_BYTES:
                break
            # Keep the newest spans: for a streamed corpus they cover the
            # most recent chunks, and the client's own rpc span still
            # brackets the whole call.
            spans = spans[max(1, len(spans) // 4):]
        if not spans or len(payload) > self.MAX_BYTES:
            return ()
        return (("nemo-spans-bin", payload),)

    def release(self) -> None:
        if not self._owned:
            return
        self._owned = False
        with _collector_lock:
            _collector_refs[0] -= 1
            t = obs.tracer()
            if _collector_refs[0] == 0 and t is not None and not t.path:
                # finish() on a pathless tracer writes nothing — it just
                # disables collection until the next traced request.
                obs_trace.finish()


class _QueryRpcError(Exception):
    """A query-layer error (unknown vocabulary name, bad run filter)
    surfacing through the Query RPC's single-flight machinery — mapped to
    INVALID_ARGUMENT at the handler boundary (including for coalesce
    subscribers, who receive the leader's failure re-raised)."""


class _Impl:
    """Method implementations; one fused-step jit cache per process.

    Trace-context propagation is per request via _SpanCollection; every
    handler acquires one and releases it in a finally.  Every WORK handler
    additionally holds an admission ticket (nemo_tpu/serve) for the span
    of its execution — Health stays ungated so readiness probes and
    wait_ready() always answer.
    """

    def __init__(self) -> None:
        self.admission = serve.controller()
        self.flights = serve.flights()
        self.batcher = serve.batcher()

    def _admit(self, context, rpc: str) -> serve.Ticket:
        """Enqueue-or-reject, then wait for an execution slot.  Rejections
        abort with RESOURCE_EXHAUSTED (queue full — the client should shed
        or back off by the `nemo-retry-after-s` trailing-metadata hint) or
        UNAVAILABLE (draining — the client should find another replica).
        While queued, the wait polls so a dead client's slot request is
        abandoned instead of granted to a hung handler."""
        tenant = _tenant_of(context)
        t0_us = time.perf_counter_ns() // 1000
        try:
            ticket = self.admission.enqueue(tenant)
        except serve.AdmissionRejected as ex:
            context.set_trailing_metadata(
                (("nemo-retry-after-s", f"{ex.retry_after_s:.3f}"),)
            )
            context.abort(
                grpc.StatusCode.UNAVAILABLE
                if ex.reason == "draining"
                else grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"{rpc} not admitted: {ex.reason}; "
                f"retry after ~{ex.retry_after_s:.1f}s",
            )
        deadline = time.monotonic() + serve.admission.queue_timeout_seconds()
        while not ticket.wait(0.2):
            if not context.is_active():
                ticket.cancel()
                context.abort(grpc.StatusCode.CANCELLED, "client went away while queued")
            if time.monotonic() > deadline:
                ticket.cancel()
                obs.metrics.inc("serve.rejected")
                obs.metrics.inc("serve.rejected.queue_timeout")
                # A timeout is a shed the queue took too long to admit —
                # charge the tenant's SLO budget like any other refusal.
                self.admission.record_shed(tenant, "queue_timeout")
                context.set_trailing_metadata(
                    (("nemo-retry-after-s", f"{self.admission.retry_after_s():.3f}"),)
                )
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"{rpc} queued past the admission timeout",
                )
        # The queued interval as a span: a stitched client trace shows
        # admission wait next to exec instead of an unexplained gap.
        obs.add_span(
            "serve:admission",
            t0_us,
            time.perf_counter_ns() // 1000 - t0_us,
            {"tenant": tenant, "rpc": rpc},
        )
        return ticket

    def _admit_traced(self, context, rpc: str) -> tuple:
        """(ticket, span collection) — the collection FIRST, so the
        admission-wait span lands in the traced client's stitched set
        rather than only the flight ring; released on an admission abort
        (context.abort raises) so a rejected request can't leak the
        pathless collector."""
        col = _SpanCollection(context)
        try:
            return self._admit(context, rpc), col
        except BaseException:
            col.release()
            raise

    def health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        col = _SpanCollection(context)
        t0 = time.perf_counter()
        try:
            with obs.span("serve:Health", trace_id=col.tid):
                import jax

                devs = jax.devices()
                resp = pb.HealthResponse(
                    platform=devs[0].platform, device_count=len(devs), version=VERSION
                )
            # The metrics snapshot rides every Health response (trailing
            # metadata — no proto bump): operators inspect sidecar state
            # (dispatch counts, compile-cache hits, step latencies) through
            # any client's health() without SSH.
            context.set_trailing_metadata(
                (("nemo-metrics-bin", json.dumps(obs.metrics.snapshot()).encode("utf-8")),)
                + col.trailing()
            )
            return resp
        finally:
            _rpc_observed("Health", t0, col.tid)
            col.release()

    def _run_step(
        self, pre, post, static: dict, chunk: int, trace_id: str | None
    ) -> pb.AnalyzeResponse:
        """One fused analysis_step dispatch -> wire response; shared by the
        array-upload paths (Analyze/AnalyzeStream) and the server-side
        corpus path (AnalyzeDir)."""
        import jax

        from nemo_tpu.models.pipeline_model import analysis_step

        from nemo_tpu.backend.jax_backend import _pack_out_default, _unpack_summary

        b = int(pre.is_goal.shape[0])
        t0 = time.perf_counter()
        # The server owns the device, so it decides the transfer folding
        # (like LocalExecutor.run): with pack_out the program's bool
        # outputs — including this path's diff tail — arrive as ONE
        # bit-packed device->host copy and unpack here, before the wire
        # codec (which bit-packs bools again for transport).  Clients are
        # unaffected; this static never comes from the request.
        static = dict(static, pack_out=bool(_pack_out_default()))
        with obs.span("serve:analysis_step", chunk=chunk, rows=b, trace_id=trace_id):
            out = analysis_step(pre, post, **static)
            out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        obs.metrics.inc("serve.analyze_chunks")
        obs.metrics.observe("serve.step_s", dt)
        obs.metrics.observe("serve.batch_rows", b)
        if "packed_summary" in out:
            out = dict(out)
            out.update(
                _unpack_summary(
                    out.pop("packed_summary"),
                    b=b,
                    v=int(static["v"]),
                    t=int(static["num_tables"]),
                    # Derive from the same dict used for dispatch so the
                    # packed layout and the unpack can never diverge if the
                    # codec ever starts carrying with_diff (ADVICE r4 #2).
                    with_diff=bool(static.get("with_diff", True)),
                )
            )
        return codec.outputs_to_pb(out, chunk=chunk, step_seconds=dt)

    def _analyze_one(
        self, request: pb.AnalyzeRequest, trace_id: str | None = None
    ) -> pb.AnalyzeResponse:
        pre = codec.batch_arrays_from_pb(request.pre)
        post = codec.batch_arrays_from_pb(request.post)
        static = codec.static_from_pb(request.static)
        return self._run_step(pre, post, static, int(request.chunk), trace_id)

    def analyze(self, request: pb.AnalyzeRequest, context) -> pb.AnalyzeResponse:
        t0 = time.perf_counter()
        ticket, col = self._admit_traced(context, "Analyze")
        try:
            resp = self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return resp
        finally:
            _rpc_observed("Analyze", t0, col.tid)
            col.release()
            ticket.release()

    def analyze_stream(self, request_iterator, context):
        # Sequential device dispatch preserves chunk arrival order; gRPC's
        # flow control provides the backpressure (SURVEY.md §7 hard part 6).
        # One admission ticket covers the whole stream: a streaming session
        # is one continuous occupancy of the device, not per-chunk work.
        t0 = time.perf_counter()
        ticket, col = self._admit_traced(context, "AnalyzeStream")
        try:
            for request in request_iterator:
                yield self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
        finally:
            _rpc_observed("AnalyzeStream", t0, col.tid)
            col.release()
            ticket.release()

    def analyze_dir(self, request: dict, context) -> pb.AnalyzeResponse:
        """Server-side corpus analysis: the request names a Molly directory
        reachable from THIS process (the sidecar normally shares the host
        or a mounted corpus volume with its clients), so repeated client
        sessions over the same corpus skip both the array upload AND the
        JSON parse — the sidecar consults its own persistent corpus store
        (nemo_tpu/store, ``--corpus-cache``/``NEMO_CORPUS_CACHE``) and
        mmap-loads on every session after the first.

        Wire shape: the request is a JSON object (``{"dir": ..., optional
        "corpus_cache": ..., optional "result_cache": ...}``) carried
        through a generic-handler JSON deserializer — no protoc
        regeneration needed — and the response is the standard
        AnalyzeResponse the Analyze RPC returns.

        Response caching: when the sidecar's result cache resolves
        (``--result-cache``/``NEMO_RESULT_CACHE``) and the corpus was
        served by the store, the serialized response is cached
        content-addressed on (segment fingerprints, statics, wire
        version, analysis ABI) — a repeat session gets the stored bytes
        with ZERO device dispatches, flagged ``nemo-rcache: hit`` in the
        trailing metadata (hit/miss/off streams back on every call).
        ``result_cache`` in the request can only opt OUT ("off"), like
        ``corpus_cache``."""
        t0 = time.perf_counter()
        if not isinstance(request, dict):
            # Valid JSON but not an object ('[1]', '"x"') — the
            # deserializer accepted it; fail with the clear status, not
            # an AttributeError surfacing as UNKNOWN.
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "AnalyzeDir request must be a JSON object",
            )
        d = request.get("dir", "")
        if not d or not os.path.isdir(d):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"not a directory on the sidecar host: {d!r}",
            )
        ticket, col = self._admit_traced(context, "AnalyzeDir")
        try:
            payload, meta = self._dir_payload(request, d, col.tid, ticket, context)
            md = col.trailing() + (
                ("nemo-rcache", meta["rcache"]),
                ("nemo-coalesce", meta["coalesce"]),
            )
            if "fleet" in meta:
                md = md + (("nemo-fleet", meta["fleet"]),)
            context.set_trailing_metadata(md)
            # The SERIALIZED payload goes to the wire verbatim (the
            # handler's serializer passes bytes through): map-field
            # serialization order is process-nondeterministic, so a
            # decode/re-encode here would break the fleet's byte-identical
            # response contract the moment a follower REPLICA relays a
            # leader's payload — and skipping it saves a round trip on
            # every response anyway.
            return payload
        finally:
            _rpc_observed("AnalyzeDir", t0, col.tid)
            col.release()
            ticket.release()

    def _ingest_dir(self, request: dict, d: str):
        """Resolve a directory request to dispatchable arrays:
        (pre, post, static, seg_meta).  Store authority is the OPERATOR's
        (--corpus-cache / NEMO_CORPUS_CACHE): a client may opt OUT for its
        request (corpus_cache="off"), but can never enable or redirect a
        server-side store the operator disabled — the request names a
        client-chosen server path a full corpus mirror would be written
        to."""
        from nemo_tpu.analysis.pipeline import _ingest
        from nemo_tpu.models.pipeline_model import BatchArrays
        from nemo_tpu.store import corpus_cache_dir, resolve_store

        req_cache = request.get("corpus_cache")
        client_opt_out = req_cache is not None and corpus_cache_dir(req_cache) is None
        store = None if client_opt_out else resolve_store()
        # Warm array-only path first: the handler dispatches arrays
        # + statics, so a hit skips the per-run MollyOutput build.
        nc = store.load_corpus(d) if store is not None else None
        if nc is None:
            # Cold/stale (already counted by load_corpus above): the
            # pipeline's canonical parse+populate with a pre-parse
            # snapshot — one policy, shared, not a server-side copy;
            # consult_store=False so the miss is not probed and counted a
            # second time.
            molly = _ingest(d, use_packed=True, store=store, consult_store=False)
            nc = getattr(molly, "native_corpus", None)
        if nc is not None:
            from nemo_tpu.ingest.native import corpus_step_static

            pre = BatchArrays.from_packed(nc.pre)
            post = BatchArrays.from_packed(nc.post)
            static = corpus_step_static(nc)
            seg_meta = getattr(nc, "store_segments", None)
        else:  # object-loader fallback (no native lib, cold store)
            from nemo_tpu.models.pipeline_model import pack_molly_for_step

            pre, post, static = pack_molly_for_step(molly)
            seg_meta = getattr(molly, "store_segments", None)
        obs.metrics.inc("serve.analyze_dir")
        return pre, post, static, seg_meta

    def _dir_payload(
        self,
        request: dict,
        d: str,
        trace_id: str | None,
        ticket: serve.Ticket,
        context=None,
    ) -> tuple[bytes, dict]:
        """One directory request -> (serialized AnalyzeResponse, meta with
        'rcache' and 'coalesce' statuses).  Shared by AnalyzeDir and
        AnalyzeDirStream.

        Coalescing (ISSUE 8): the corpus's content address — the exact key
        the result cache blobs under (segment fingerprints + statics +
        wire version + analysis ABI, analysis/delta.py:blob_cache_key) —
        keys a single-flight table.  Concurrent identical requests attach
        as subscribers to the first arrival's execution and receive its
        byte-identical payload; a subscriber RELEASES its admission slot
        before waiting (it consumes no execution capacity) and its ticket
        release is idempotent, so the handler's finally stays correct.
        Anonymous corpora (no store -> no fingerprints) key to None:
        uncacheable and uncoalesceable, exactly like the rcache tiers."""
        from nemo_tpu.analysis.delta import blob_cache_key
        from nemo_tpu.store.rcache import resolve_result_cache, result_cache_dir

        with obs.span("serve:AnalyzeDir", dir=os.path.basename(d), trace_id=trace_id):
            pre, post, static, seg_meta = self._ingest_dir(request, d)

            # Response cache: operator authority like the store — resolved
            # from the sidecar's own env, request can only opt out.  Keyed
            # on segment fingerprints + statics + wire version, so a stale
            # store or a kernel ABI bump can never serve old bytes.
            req_rc = request.get("result_cache")
            rc_opt_out = req_rc is not None and result_cache_dir(req_rc) is None
            rc = None if rc_opt_out else resolve_result_cache()
            content_key = blob_cache_key(
                "analyze_dir",
                seg_meta,
                {"static": {k: int(v) for k, v in static.items()}, "wire": VERSION},
            )

            def _serve_cached(cached: bytes) -> bytes:
                resp = pb.AnalyzeResponse.FromString(cached)
                # The stored wall is the POPULATING run's; a served hit
                # dispatched nothing.
                resp.step_seconds = 0.0
                obs.metrics.inc("serve.analyze_dir_cached")
                return resp.SerializeToString()

            def _run_and_publish() -> bytes:
                resp = self._run_step(pre, post, static, chunk=0, trace_id=trace_id)
                p = resp.SerializeToString()
                if rc is not None and content_key is not None:
                    rc.put_blob("analyze_dir", content_key, p)
                return p

            def _execute() -> tuple[bytes, dict]:
                rc_status = "off"
                meta_extra: dict = {}
                payload = None
                if rc is not None and content_key is not None:
                    cached = rc.load_blob("analyze_dir", content_key)
                    if cached is not None:
                        payload = _serve_cached(cached)
                        rc_status = "hit"
                    else:
                        rc_status = "miss"
                if payload is None:
                    if (
                        rc is not None
                        and content_key is not None
                        and rc.lease_root is not None
                    ):
                        # Fleet single-flight (ISSUE 14): the shared tier
                        # carries a leader lease on this content address,
                        # so a herd hitting EVERY replica of a cold corpus
                        # still costs the fleet one analysis.  A follower
                        # returns the leader's flight bytes VERBATIM
                        # (cross-replica coalesce semantics — the herd's
                        # responses are byte-identical, step wall
                        # included); only a LATER request is the rcache's
                        # zero-walled hit.
                        payload, fleet = self._fleet_single_flight(
                            rc, content_key, _run_and_publish, context
                        )
                        meta_extra["fleet"] = fleet
                    else:
                        payload = _run_and_publish()
                return payload, {"rcache": rc_status, **meta_extra}

            if content_key is None:
                payload, meta = _execute()
                meta["coalesce"] = "off"
                obs.metrics.inc("serve.coalesce.off")
                return payload, meta
            role, flight = self.flights.join(content_key)
            if role == "leader":
                try:
                    payload, meta = _execute()
                except BaseException as ex:
                    self.flights.fail(flight, ex)
                    raise
                self.flights.complete(flight, payload, meta)
                meta = dict(meta, coalesce="leader")
                obs.metrics.inc("serve.coalesce.leader")
                return payload, meta
            # Subscriber: free the execution slot — we only wait on bytes.
            # The wait is liveness-checked (a dead client's thread returns
            # to the pool) and bounded at the client's own RPC deadline;
            # live subscribers DO each hold one handler-pool thread, which
            # the pool sized from the admission capacity bounds.
            ticket.release()
            obs.metrics.inc("serve.coalesce.hit")
            obs.metrics.inc(f"serve.tenant.{ticket.tenant}.coalesced")
            log.debug(
                "serve.coalesced", dir=d, key=content_key[:12], trace_id=trace_id
            )
            payload, meta = flight.wait_result(
                is_alive=context.is_active if context is not None else None
            )
            meta = dict(meta, coalesce="hit")
            # The fleet role is the LEADER handler's relationship to the
            # shared tier, not this subscriber's: inheriting it would
            # report N "nemo-fleet: leader" responses (and inflate the
            # client-side fleet counters N-fold) for one fleet analysis.
            meta.pop("fleet", None)
            return payload, meta

    def _fleet_single_flight(
        self, rc, content_key: str, run, context
    ) -> tuple[bytes, str]:
        """Cross-replica single-flight on the shared cache tier (ISSUE 14).

        The PR-8 coalesce leader's lease moves into the shared tier: a
        lease FILE keyed on the tier-3 content address (store/rcache.py:
        Lease under ``<shared>/lease/analyze_dir/``).  The replica that
        wins the ``O_CREAT|O_EXCL`` create leads — it executes ``run()``
        (which publishes the blob to the shared tier) under a heartbeat
        thread refreshing the lease every TTL/3.  Every other replica's
        identical request FOLLOWS: it polls for the leader's published
        blob (cheap existence probe, one verified read on appearance) and
        for the lease's death — a leader that crashes stops heartbeating,
        the lease goes stale past ``NEMO_LEASE_TTL_S``, and the first
        follower to steal it RE-ELECTS itself leader.  A follower that
        exhausts its wait bound (the subscriber deadline,
        serve/coalesce.py:Flight.WAIT_TIMEOUT_S) or whose client died
        executes locally as the safety valve: the key is a pure content
        address, so a duplicate analysis is a counted inefficiency
        (``serve.fleet.wait_timeout``), never a conflict.

        Returns ``(payload, role)`` — role ``leader``/``timeout`` payloads
        are fresh serialized responses; ``follower`` payloads are the
        leader's serialized bytes and the caller relays them VERBATIM
        (the fleet's byte-identical response contract — re-serializing
        would diverge on process-dependent map-field order; only a LATER
        request is the rcache's zero-walled hit).  In-process duplicates
        never reach here concurrently: the local SingleFlight table
        already coalesced them onto one handler.
        """
        import threading as _threading

        from nemo_tpu.store.rcache import Lease

        lease = Lease(rc.lease_root, "analyze_dir", content_key, owner=_replica_id())
        deadline = time.monotonic() + serve.coalesce.Flight.WAIT_TIMEOUT_S
        followed = False
        flight_id = content_key[:16]
        t0_us = time.perf_counter_ns() // 1000
        # Read the leader's identity BEFORE serving its bytes: the lease is
        # released right after publish, so a post-return read would usually
        # find nothing to link the follower's trace to.
        leader_owner: str | None = None
        while True:
            # Blob BEFORE lease: a finished leader publishes and only then
            # releases, so a waiter waking between the two must serve the
            # published bytes, not win the freed lease and re-run.
            if rc.blob_present("analyze_dir", content_key):
                cached = rc.load_blob("analyze_dir", content_key)
                if cached is not None:
                    if not followed:
                        obs.metrics.inc("serve.fleet.follower")
                    # Span-link to the leader's flight: the follower's
                    # trace names the flight id (shared with the leader's
                    # serve:fleet_leader span args) and the leader replica
                    # that computed the bytes — not a dead end.
                    obs.add_span(
                        "serve:fleet_follower",
                        t0_us,
                        time.perf_counter_ns() // 1000 - t0_us,
                        {
                            "flight": flight_id,
                            "span_link": f"flight:{flight_id}",
                            "leader": leader_owner or lease.read_owner(),
                        },
                    )
                    return cached, "follower"
                # Present but unreadable/corrupt (counted stale by the
                # cache): fall through — the next acquire/poll decides.
            acquired = lease.try_acquire()
            if not acquired and lease.broken:
                # Shared-tier infrastructure failure (unwritable mount):
                # nobody can lead OR publish there — run locally now
                # rather than waiting out the follower deadline for a
                # publish that can never arrive.
                obs.metrics.inc("serve.fleet.lease_error")
                log.warning(
                    "serve.fleet_lease_error", key=content_key[:12],
                    detail="shared lease tier unusable; executing locally",
                )
                return run(), "lease_error"
            if acquired:
                obs.metrics.inc("serve.fleet.leader")
                log.debug(
                    "serve.fleet_leader", key=content_key[:12], owner=lease.owner
                )
                stop = _threading.Event()

                def _beat() -> None:
                    while not stop.wait(lease.ttl_s / 3.0):
                        lease.heartbeat()

                hb = _threading.Thread(
                    target=_beat, daemon=True, name="nemo-lease-heartbeat"
                )
                hb.start()
                try:
                    with obs.span(
                        "serve:fleet_leader", flight=flight_id, owner=lease.owner
                    ):
                        return run(), "leader"
                finally:
                    stop.set()
                    lease.release()
            if not followed:
                followed = True
                obs.metrics.inc("serve.fleet.follower")
                leader_owner = lease.read_owner()
                log.debug(
                    "serve.fleet_follower", key=content_key[:12],
                    detail="another replica leads this content address; "
                    "waiting on the shared tier",
                )
            if context is not None and not context.is_active():
                # Dead client: nobody is listening, and the live leader is
                # computing the identical key anyway — free this handler
                # thread WITHOUT running a duplicate analysis (the local
                # coalesce subscriber's is_alive precedent).
                obs.metrics.inc("serve.fleet.client_gone")
                raise TimeoutError(
                    f"client went away waiting on fleet flight {content_key[:12]}"
                )
            if time.monotonic() > deadline:
                obs.metrics.inc("serve.fleet.wait_timeout")
                log.warning(
                    "serve.fleet_wait_timeout", key=content_key[:12],
                    detail="leader neither published nor expired inside the "
                    "wait bound; executing locally (duplicate, not stale)",
                )
                return run(), "timeout"
            time.sleep(min(0.25, max(0.02, lease.ttl_s / 10.0)))

    def analyze_dir_stream(self, request: dict, context):
        """Server-streaming AnalyzeDir (ISSUE 8): the request names one or
        more directories (``{"dirs": [...]}``, or the unary ``{"dir":
        ...}`` shape) and the response stream pushes JSON events as the
        work progresses instead of one terminal blob:

          ``{"event": "queued", "dir", "position"}``   admission wait
          ``{"event": "admitted", "dir"}``             slot granted
          ``{"event": "phase", "dir", "phase"}``       ingest/analyze
          ``{"event": "result", "dir", "ordinal", "rcache", "coalesce",
             "response_b64"}``                         one family done
          ``{"event": "error", "dir", "status", "detail", ...}``
          ``{"event": "done", "results", "errors"}``   terminal marker

        Directories are analyzed CONCURRENTLY (a small per-request worker
        pool, ``NEMO_SERVE_STREAM_WORKERS``), each under its OWN admission
        ticket, so results stream in completion order — a cached or
        coalesced family lands while a cold one is still compiling — and
        per-directory admission rejections surface as per-family error
        events, not a dead stream."""
        import base64
        import queue as _queue
        import threading

        t0 = time.perf_counter()
        if not isinstance(request, dict):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "AnalyzeDirStream request must be a JSON object",
            )
        dirs = request.get("dirs")
        if dirs is None:
            dirs = [request["dir"]] if request.get("dir") else []
        if not isinstance(dirs, list) or not dirs:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "AnalyzeDirStream request needs a non-empty 'dirs' list",
            )
        for d in dirs:
            if not isinstance(d, str) or not os.path.isdir(d):
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"not a directory on the sidecar host: {d!r}",
                )
        if request.get("watch") is not None:
            yield from self._watch_stream(request, dirs, context, t0)
            return
        tenant = _tenant_of(context)
        col = _SpanCollection(context)
        events: _queue.Queue = _queue.Queue()
        n_workers = min(len(dirs), serve.admission.stream_workers_default())
        work: _queue.Queue = _queue.Queue()
        for i, d in enumerate(dirs):
            work.put((i, d))

        def worker() -> None:
            while True:
                try:
                    i, d = work.get_nowait()
                except _queue.Empty:
                    return
                ticket = None
                try:
                    ticket = self.admission.enqueue(tenant)
                    last_pos = None
                    deadline = time.monotonic() + serve.admission.queue_timeout_seconds()
                    while not ticket.wait(0.2):
                        if not context.is_active() or time.monotonic() > deadline:
                            ticket.cancel()
                            raise serve.AdmissionRejected(
                                "queue_timeout", self.admission.retry_after_s()
                            )
                        pos = ticket.position()
                        if pos != last_pos:
                            last_pos = pos
                            events.put(
                                {"event": "queued", "dir": d, "position": pos}
                            )
                    events.put({"event": "admitted", "dir": d})
                    events.put({"event": "phase", "dir": d, "phase": "analyze"})
                    payload, meta = self._dir_payload(
                        {**request, "dir": d}, d, col.tid, ticket, context
                    )
                    obs.metrics.inc("serve.stream.results")
                    ev = {
                        "event": "result",
                        "dir": d,
                        "ordinal": i,
                        "rcache": meta.get("rcache", "off"),
                        "coalesce": meta.get("coalesce", "off"),
                        "response_b64": base64.b64encode(payload).decode("ascii"),
                    }
                    if "fleet" in meta:
                        ev["fleet"] = meta["fleet"]
                    events.put(ev)
                except serve.AdmissionRejected as ex:
                    events.put(
                        {
                            "event": "error",
                            "dir": d,
                            "ordinal": i,
                            "status": "RESOURCE_EXHAUSTED",
                            "detail": ex.reason,
                            "retry_after_s": round(ex.retry_after_s, 3),
                        }
                    )
                except BaseException as ex:  # one family's failure, not the stream's
                    events.put(
                        {
                            "event": "error",
                            "dir": d,
                            "ordinal": i,
                            "status": "INTERNAL",
                            "detail": f"{type(ex).__name__}: {ex}",
                        }
                    )
                finally:
                    if ticket is not None:
                        ticket.release()

        threads = [
            threading.Thread(target=worker, daemon=True, name=f"nemo-serve-stream-{k}")
            for k in range(n_workers)
        ]
        # Stream presence (ISSUE 9 satellite): the handler itself holds no
        # admission ticket (its per-directory workers do), so it registers
        # with the controller's stream counter — the SIGTERM drain waits
        # for it, guaranteeing the terminal `done` event is yielded before
        # the server stops instead of severing a mid-flight stream.
        self.admission.begin_stream()
        try:
            for t in threads:
                t.start()
            done = errors = 0
            while done + errors < len(dirs):
                ev = events.get()
                if ev["event"] == "result":
                    done += 1
                elif ev["event"] == "error":
                    errors += 1
                obs.metrics.inc("serve.stream.events")
                yield ev
            yield {"event": "done", "results": done, "errors": errors}
        finally:
            for t in threads:
                t.join(timeout=5.0)
            self.admission.end_stream()
            _rpc_observed("AnalyzeDirStream", t0, col.tid)
            col.release()

    def _watch_stream(self, request: dict, dirs: list, context, t0: float):
        """Live watch mode of AnalyzeDirStream (ISSUE 15): instead of a
        one-shot analysis the request attaches the stream to a live
        :class:`~nemo_tpu.watch.watcher.Watcher` tailing ONE sweep
        directory mid-sweep.  Request shape::

            {"dirs": ["/sweep"], "watch": {"results_root": "/reports",
             "max_updates": 0, "poll_s": 0.5, "debounce_s": 0.25,
             "figures": "all", "injector": "auto"}}

        Events: one ``{"event": "watching", ...}`` acknowledgement, then a
        ``report_update`` per published update (ordinal, new/total runs,
        O(new runs) evidence — runs mapped / segments cached / kernel
        dispatch delta — and changed-section sha256 digests; the report
        tree itself lives at ``results_root`` on the sidecar host, the
        same trust model as the request's corpus paths), ``watch_error``
        for a failed cycle (the watch continues), and a terminal ``done``
        when ``max_updates`` is reached or the client goes away.

        The session holds ONE admission slot for its whole lifetime (it
        is one long-running analysis job occupying backend capacity) plus
        stream presence, so a drain waits for the terminal event exactly
        like the one-shot stream."""
        import queue as _queue
        import threading

        wopts = request.get("watch")
        if wopts is True:
            wopts = {}
        if not isinstance(wopts, dict):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "watch must be a JSON object of watch options",
            )
        if len(dirs) != 1:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "watch mode takes exactly one directory",
            )
        results_root = wopts.get("results_root")
        if not results_root or not isinstance(results_root, str):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "watch mode needs a 'results_root' (sidecar-host path the "
                "live report publishes under)",
            )
        d = dirs[0]
        ticket, col = self._admit_traced(context, "AnalyzeDirStream")
        self.admission.begin_stream()
        watcher = None
        th = None
        try:
            from nemo_tpu.backend.jax_backend import JaxBackend
            from nemo_tpu.watch import Watcher, WatchConfig

            cfg_kw = {
                k: wopts[k]
                for k in (
                    "poll_s",
                    "debounce_s",
                    "figures",
                    "injector",
                    "initial_wait_s",
                )
                if wopts.get(k) is not None
            }
            cfg = WatchConfig(
                max_updates=int(wopts.get("max_updates", 0) or 0), **cfg_kw
            )
            watcher = Watcher(d, results_root, JaxBackend, cfg)
            q = watcher.subscribe()
            crash: list[BaseException] = []

            def _run_watcher() -> None:
                try:
                    watcher.run()
                except BaseException as ex:  # surfaced to the client below
                    crash.append(ex)

            th = threading.Thread(
                target=_run_watcher, daemon=True, name="nemo-serve-watch"
            )
            th.start()
            obs.metrics.inc("serve.watch.sessions")
            yield {
                "event": "watching",
                "dir": d,
                "results_root": results_root,
                "poll_s": cfg.poll_s,
                "debounce_s": cfg.debounce_s,
                "max_updates": cfg.max_updates,
            }
            updates = 0
            while context.is_active():
                try:
                    ev = q.get(timeout=0.2)
                except _queue.Empty:
                    if not th.is_alive() and q.empty():
                        break  # watcher finished (max_updates reached)
                    continue
                if ev.get("event") == "report_update":
                    updates += 1
                obs.metrics.inc("serve.stream.events")
                yield ev
            if context.is_active():
                # A crashed watcher thread (setup-level failure — e.g. the
                # sweep directory never became sniffable) must NOT read as
                # a cleanly finished session: report it before the
                # terminal marker.
                if crash:
                    ex = crash[0]
                    obs.metrics.inc("serve.watch.failed")
                    yield {
                        "event": "watch_error",
                        "dir": d,
                        "detail": f"{type(ex).__name__}: {ex}",
                        "fatal": True,
                    }
                yield {
                    "event": "done",
                    "dir": d,
                    "updates": updates,
                    "errors": 1 if crash else 0,
                }
        finally:
            if watcher is not None:
                watcher.stop()
            if th is not None:
                # The watcher may be mid-run_debug; it is a daemon thread
                # and checks the stop flag at the next poll boundary.
                th.join(timeout=5.0)
            ticket.release()
            self.admission.end_stream()
            _rpc_observed("AnalyzeDirStream", t0, col.tid)
            col.release()

    def query(self, request: dict, context) -> bytes:
        """Ad-hoc provenance query RPC (ISSUE 20): the request is a JSON
        object ``{"dir": ..., "query": <text>, optional "corpus_cache",
        optional "result_cache"}`` — protoc-free like AnalyzeDir — and the
        response is the JSON result document (nemo_tpu/query) as bytes.

        Admission, tracing, caching, and coalescing follow the AnalyzeDir
        contract: the sidecar ingests the directory through its own corpus
        store, the query executes through ``execute_query`` (whose
        two-tier rcache is content-addressed on segment fingerprints + the
        query AST hash), concurrent identical requests single-flight on
        that same content address, and the trailing metadata carries
        ``nemo-rcache``/``nemo-coalesce`` statuses.  A malformed query is
        INVALID_ARGUMENT with the parser's loud message, never an empty
        result."""
        t0 = time.perf_counter()
        if not isinstance(request, dict):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "Query request must be a JSON object",
            )
        d = request.get("dir", "")
        if not d or not os.path.isdir(d):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"not a directory on the sidecar host: {d!r}",
            )
        text = request.get("query", "")
        if not text or not isinstance(text, str):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "Query request needs a non-empty 'query' string",
            )
        from nemo_tpu.query import QueryError, parse_query, plan_query

        try:
            q = parse_query(text)
            plan = plan_query(q)
        except QueryError as ex:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"query error: {ex}")
        ticket, col = self._admit_traced(context, "Query")
        try:
            try:
                payload, meta = self._query_payload(request, d, q, plan, col.tid)
            except _QueryRpcError as ex:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, f"query error: {ex}"
                )
            context.set_trailing_metadata(
                col.trailing()
                + (
                    ("nemo-rcache", meta["rcache"]),
                    ("nemo-coalesce", meta["coalesce"]),
                )
            )
            return payload
        finally:
            _rpc_observed("Query", t0, col.tid)
            col.release()
            ticket.release()

    def _query_payload(
        self, request: dict, d: str, q, plan, trace_id: str | None
    ) -> tuple[bytes, dict]:
        """One query request -> (JSON document bytes, meta).  Single-flight
        on the query's content address — the same (segment fingerprints +
        AST hash) key execute_query blobs the full result under — so a
        herd of identical ad-hoc queries costs one execution."""
        from nemo_tpu.analysis.delta import blob_cache_key
        from nemo_tpu.analysis.pipeline import _ingest
        from nemo_tpu.query import QueryError
        from nemo_tpu.query.engine import execute_query
        from nemo_tpu.store import corpus_cache_dir, resolve_store
        from nemo_tpu.store.rcache import result_cache_dir

        with obs.span("serve:Query", dir=os.path.basename(d), trace_id=trace_id):
            req_cache = request.get("corpus_cache")
            client_opt_out = (
                req_cache is not None and corpus_cache_dir(req_cache) is None
            )
            store = None if client_opt_out else resolve_store()
            molly = _ingest(d, use_packed=True, store=store)
            req_rc = request.get("result_cache")
            rc_opt_out = req_rc is not None and result_cache_dir(req_rc) is None
            seg_meta = getattr(molly, "store_segments", None)
            content_key = (
                None
                if rc_opt_out
                else blob_cache_key("query", seg_meta, {"plan": plan.key})
            )
            obs.metrics.inc("serve.query")

            def _execute() -> tuple[bytes, dict]:
                try:
                    doc = execute_query(q, molly, use_cache=not rc_opt_out)
                except QueryError as ex:
                    # Unknown vocabulary name etc.: surface as the RPC
                    # error contract, not an UNKNOWN traceback.
                    raise _QueryRpcError(str(ex)) from ex
                rstat = doc.get("stats", {}).get("cache", "off")
                return json.dumps(doc, sort_keys=True).encode("utf-8"), {
                    "rcache": rstat
                }

            if content_key is None:
                payload, meta = _execute()
                meta["coalesce"] = "off"
                obs.metrics.inc("serve.coalesce.off")
                return payload, meta
            role, flight = self.flights.join(content_key)
            if role == "leader":
                try:
                    payload, meta = _execute()
                except BaseException as ex:
                    self.flights.fail(flight, ex)
                    raise
                self.flights.complete(flight, payload, meta)
                obs.metrics.inc("serve.coalesce.leader")
                return payload, dict(meta, coalesce="leader")
            obs.metrics.inc("serve.coalesce.hit")
            payload, meta = flight.wait_result()
            return payload, dict(meta, coalesce="hit")

    def kernel(self, request: pb.KernelRequest, context) -> pb.KernelResponse:
        """Named device-kernel dispatch for the ServiceBackend: the request's
        (verb, arrays, params) triple runs through the same LocalExecutor the
        in-process JaxBackend uses, so both deployments execute identical
        device code.  Row-independent verbs route through the serving
        tier's continuous batcher (nemo_tpu/serve/batch.py): compatible
        dispatches from DIFFERENT in-flight requests merge into one padded
        device launch and demux per request."""
        from nemo_tpu.backend.jax_backend import LocalExecutor

        t_rpc = time.perf_counter()
        ticket, col = self._admit_traced(context, "Kernel")
        try:
            verb, arrays, params = codec.kernel_request_from_pb(request)
            if verb not in LocalExecutor.VERBS:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel verb {verb!r}")
            t0 = time.perf_counter()
            try:
                # LocalExecutor is stateless; the jit caches live on the
                # module-level kernel functions.  Its own kernel:<verb> span
                # rides home in the trailing metadata.
                with obs.span("serve:Kernel", verb=verb, trace_id=col.tid):
                    out = self.batcher.run(LocalExecutor(), verb, arrays, params)
            except KeyError as ex:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"missing kernel input: {ex}")
            obs.metrics.inc("serve.kernel_calls")
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return codec.kernel_response_to_pb(out, step_seconds=time.perf_counter() - t0)
        finally:
            _rpc_observed("Kernel", t_rpc, col.tid)
            col.release()
            ticket.release()


def make_server(port: int = 0, max_workers: int | None = None) -> tuple[grpc.Server, int]:
    """Build (but don't start) the sidecar server; returns (server, port).

    max_workers is the gRPC HANDLER pool; the default is derived from the
    admission tier's FULL capacity (max_inflight + max_queue + headroom
    for Health/streams, capped at 256): every request the admission
    contract promises to count, position, fair-schedule, or shed with a
    retry-after must actually reach a handler — a narrower pool would park
    the excess invisibly in grpc's work queue, uncounted and untimed,
    which is exactly the failure mode the admission queue exists to
    prevent.  The pool threads are cheap (all but max_inflight of them are
    parked in the admission wait)."""
    impl = _Impl()
    if max_workers is None:
        ctl = impl.admission
        max_workers = min(ctl.max_inflight + ctl.max_queue + 8, 256)
    handlers = {
        "Health": grpc.unary_unary_rpc_method_handler(
            impl.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
        "Analyze": grpc.unary_unary_rpc_method_handler(
            impl.analyze,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "AnalyzeStream": grpc.stream_stream_rpc_method_handler(
            impl.analyze_stream,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        # JSON-carried request (generic handlers accept any serializer, so
        # no protoc regeneration is needed for the path-only payload).
        # The response serializer passes ALREADY-SERIALIZED bytes through:
        # the handler returns cached/coalesced flight payloads verbatim
        # (cross-replica byte identity — map fields re-serialize in a
        # process-dependent order, so round-tripping would diverge).
        "AnalyzeDir": grpc.unary_unary_rpc_method_handler(
            impl.analyze_dir,
            request_deserializer=lambda b: json.loads(b.decode("utf-8")),
            response_serializer=lambda m: (
                m if isinstance(m, bytes) else m.SerializeToString()
            ),
        ),
        # Server-streaming AnalyzeDir (ISSUE 8): JSON request, a stream of
        # JSON progress/result events back (results carry the serialized
        # AnalyzeResponse base64-embedded) — per-family push instead of
        # one terminal blob, still protoc-free.
        "AnalyzeDirStream": grpc.unary_stream_rpc_method_handler(
            impl.analyze_dir_stream,
            request_deserializer=lambda b: json.loads(b.decode("utf-8")),
            response_serializer=lambda d: json.dumps(d).encode("utf-8"),
        ),
        # Ad-hoc query RPC (ISSUE 20): JSON request in, the query result
        # document as JSON bytes out — same protoc-free generic-handler
        # pattern as AnalyzeDir (the serializer passes bytes through).
        "Query": grpc.unary_unary_rpc_method_handler(
            impl.query,
            request_deserializer=lambda b: json.loads(b.decode("utf-8")),
            response_serializer=lambda m: (
                m if isinstance(m, bytes) else m.SerializeToString()
            ),
        ),
        "Kernel": grpc.unary_unary_rpc_method_handler(
            impl.kernel,
            request_deserializer=pb.KernelRequest.FromString,
            response_serializer=pb.KernelResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 1 << 30),
            ("grpc.max_send_message_length", 1 << 30),
            # Span trailing metadata (traced clients) can reach
            # _SpanCollection.MAX_BYTES; default metadata limits are 8 KB.
            ("grpc.max_metadata_size", 2 << 20),
        ],
    )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


def _router_main(args) -> int:
    """``--router`` mode: serve the thin fleet router instead of an
    analysis replica (nemo_tpu/serve/router.py).  No jax, no device — the
    process is bytes-plumbing plus the ring."""
    import signal

    backends = [
        b.strip()
        for b in (args.backends or os.environ.get("NEMO_FLEET_REPLICAS", "")).split(",")
        if b.strip()
    ]
    if not backends:
        log.error(
            "router.no_backends",
            detail="--router needs --backends host:port,... or NEMO_FLEET_REPLICAS",
        )
        return 2
    from nemo_tpu.serve.router import make_router_server

    # The flight recorder is on for the router too: a breaker-style
    # incident seen from the routing tier (failover storms, spill loops)
    # deserves the same postmortem capture as a replica-side one.
    if obs.flight.configure_from_env() is not None:
        log.info("flight.armed", dir=obs.flight.recorder().out_dir)
    server, port, router = make_router_server(args.port, backends)
    server.start()
    metrics_httpd = None
    if args.metrics_port:
        from nemo_tpu.obs import federation, promexp

        def _router_health() -> dict:
            states = router.backend_states()
            up = sum(1 for s in states.values() if s["up"])
            return {
                "status": "SERVING" if up else "NOT_SERVING",
                "role": "router",
                "replicas": len(states),
                "replicas_up": up,
                "backends": states,
            }

        def _fleet_metrics() -> str:
            # The federated page: router's own registry unlabeled, every
            # replica's last Health-ride snapshot {replica=...}-labeled,
            # fleet rollups + liveness gauges (obs/federation.py).
            snaps, up = router.fleet_snapshots()
            return federation.federate(snaps, up)

        metrics_httpd, mport = promexp.start_http_server(
            args.metrics_port,
            health=_router_health,
            render=_fleet_metrics,
            routes={"/autoscale": router.autoscaler.doc},
        )
        log.info(
            "metrics.listening", port=mport,
            paths=["/metrics", "/healthz", "/autoscale"],
        )
    log.info("router.listening", port=port, backends=backends)
    term = threading.Event()

    def _on_term(signum, frame):
        term.set()

    prev_handler = signal.signal(signal.SIGTERM, _on_term)
    try:
        while not term.wait(0.5):
            pass
        # The router holds no work of its own; grace covers in-flight
        # forwards (each bounded by its client's own deadline).
        stopped = server.stop(grace=serve.admission.drain_seconds())
        stopped.wait(timeout=serve.admission.drain_seconds() + 5.0)
        router.stop()
        log.info("router.drained")
        return 0
    finally:
        signal.signal(signal.SIGTERM, prev_handler)
        if metrics_httpd is not None:
            metrics_httpd.shutdown()


def _prewarm_async() -> None:
    """Warm-boot helper (ISSUE 14): compile/load the bucket-signature
    programs on a background thread at boot, so the replica's first
    requests find a hot jit cache.  With the persistent compilation cache
    enabled (always, unless NEMO_JAX_CACHE=off) a fleet scale-out replica
    pays disk-cache DESERIALIZATION here — seconds — instead of
    compile-minutes on its first cold request; serving is never blocked
    (the thread competes only for spare cycles)."""
    mode = os.environ.get("NEMO_SERVE_PREWARM", "off").strip().lower()
    if mode in ("", "0", "off", "none", "false"):
        return
    if mode not in ("chunk", "full"):
        # Warn-and-default like every serving knob (utils/env.py policy):
        # a typo must neither launch surprise background compiles nor
        # silently skip the stress program the operator asked for.
        log.warning(
            "serve.prewarm_bad_mode", value=mode,
            detail="NEMO_SERVE_PREWARM must be off|chunk|full; prewarm off",
        )
        return

    def _run() -> None:
        t0 = time.perf_counter()
        try:
            from nemo_tpu.models.case_studies import CASE_STUDIES
            from nemo_tpu.utils.prewarm import prewarm_family

            # Calibrate the platform profile FIRST (ISSUE 19): the probe
            # suite compiles the same stress-floor signatures prewarm is
            # about to warm, so a cold replica pays those compiles once —
            # and the sidecar's scheduler boots on measured constants
            # instead of seeds.  No-op when a profile already exists or
            # NEMO_PROFILE=off.
            from nemo_tpu.platform import profile as _pp

            _pp.ensure_calibrated()

            for name in sorted(CASE_STUDIES):
                # "chunk" warms only the sidecar's streamed-chunk
                # signature (the shape every pipelined client dispatches);
                # "full" adds the stress-floor fused program.
                prewarm_family(
                    name,
                    n_probe=16,
                    b_pad=2048 if mode == "full" else 16,
                    chunk_runs=512,
                    include_stress=mode == "full",
                )
            dt = time.perf_counter() - t0
            obs.metrics.gauge("serve.prewarm_s", dt)
            log.info("serve.prewarm_done", seconds=round(dt, 2), mode=mode)
        except Exception as ex:
            obs.metrics.inc("serve.prewarm_failed")
            log.warning(
                "serve.prewarm_failed", error=f"{type(ex).__name__}: {ex}"
            )

    threading.Thread(target=_run, daemon=True, name="nemo-prewarm").start()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nemo-tpu-sidecar")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument(
        "--router",
        action="store_true",
        help="serve the thin fleet ROUTER instead of an analysis replica: "
        "consistent-hash AnalyzeDir affinity over --backends with spill "
        "under load and failover on UNAVAILABLE (nemo_tpu/serve/router.py)",
    )
    parser.add_argument(
        "--backends",
        default=None,
        metavar="HOST:PORT,...",
        help="router mode's replica list (default $NEMO_FLEET_REPLICAS)",
    )
    parser.add_argument(
        "--max-workers",
        type=int,
        default=None,
        help="gRPC handler pool size (default: admission capacity — "
        "max-inflight + max-queue + headroom, capped at 256)",
    )
    parser.add_argument(
        "--max-inflight",
        type=int,
        default=None,
        metavar="N",
        help="admission cap: at most N work RPCs execute concurrently "
        "(default $NEMO_SERVE_INFLIGHT or 4); excess requests queue up to "
        "--max-queue, then reject RESOURCE_EXHAUSTED with a retry-after hint",
    )
    parser.add_argument(
        "--max-queue",
        type=int,
        default=None,
        metavar="N",
        help="admission queue bound across tenants (default $NEMO_SERVE_QUEUE "
        "or 64); 0 = reject anything that cannot start immediately",
    )
    parser.add_argument(
        "--drain-s",
        type=float,
        default=None,
        metavar="S",
        help="graceful-drain window on SIGTERM: refuse new admissions "
        "(/healthz -> NOT_SERVING), finish in-flight requests up to S "
        "seconds, then exit (default $NEMO_SERVE_DRAIN_S or 30)",
    )
    parser.add_argument(
        "--profiler-port",
        type=int,
        default=0,
        help="start jax.profiler.start_server on this port so TensorBoard/"
        "xprof can capture device traces from the running sidecar (0 = off)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe under a watchdog, CPU fallback on "
        "tunnel outage), 'cpu', 'tpu', or a concrete platform name "
        "(default: $NEMO_PLATFORM or auto)",
    )
    def _metrics_port_default() -> int:
        # Junk env warns-and-defaults to off, like every observability
        # knob: a typo here must not keep the gRPC service itself down.
        try:
            return int(os.environ.get("NEMO_METRICS_PORT", "0") or 0)
        except ValueError:
            log.warning(
                "metrics.bad_port_env",
                value=os.environ.get("NEMO_METRICS_PORT"),
                detail="NEMO_METRICS_PORT is not an integer; metrics port off",
            )
            return 0

    parser.add_argument(
        "--corpus-cache",
        default=None,
        metavar="DIR|off",
        help="server-side persistent corpus store root consulted by the "
        "AnalyzeDir RPC (default $NEMO_CORPUS_CACHE or "
        "~/.cache/nemo_tpu/corpus; 'off' disables): repeated client "
        "sessions over the same corpus directory skip upload AND parse",
    )
    parser.add_argument(
        "--result-cache",
        default=None,
        metavar="DIR|off",
        help="server-side analysis result cache consulted by the AnalyzeDir "
        "RPC (default $NEMO_RESULT_CACHE or ~/.cache/nemo_tpu/results; "
        "'off' disables): a repeat session over an unchanged stored corpus "
        "gets the cached response bytes with zero device dispatches "
        "(trailing metadata nemo-rcache: hit)",
    )
    parser.add_argument(
        "--shared-cache",
        default=None,
        metavar="DIR|off",
        help="SHARED result-cache tier for a fleet (default "
        "$NEMO_RCACHE_SHARED or off): a directory every replica reaches; "
        "publishes replicate here, reads fall back here, and the "
        "cross-replica single-flight leader lease lives here — any replica "
        "serves any warm corpus, and a cold herd costs the fleet one "
        "analysis (store/rcache.py)",
    )
    parser.add_argument(
        "--prewarm",
        default=None,
        metavar="off|chunk|full",
        help="warm-boot prewarm on a background thread (default "
        "$NEMO_SERVE_PREWARM or off): compile/disk-load the bucket-"
        "signature programs at boot — 'chunk' warms the streamed-chunk "
        "shape, 'full' adds the stress-floor fused program — so a "
        "scale-out replica adds capacity in seconds, not compile-minutes",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=_metrics_port_default(),
        help="serve Prometheus text-format metrics on http://127.0.0.1:PORT"
        "/metrics (plus /healthz mirroring the gRPC Health state) from a "
        "stdlib http.server thread; 0 disables (default: "
        "$NEMO_METRICS_PORT or off)",
    )
    args = parser.parse_args(argv)
    if args.router:
        # The router owns no device and runs no analysis: branch before
        # any platform/jax work.
        return _router_main(args)
    if args.corpus_cache is not None:
        # Env-carried like the CLI's knob, so the AnalyzeDir handler and the
        # store module resolve identically in every process shape.
        os.environ["NEMO_CORPUS_CACHE"] = args.corpus_cache
    if args.result_cache is not None:
        os.environ["NEMO_RESULT_CACHE"] = args.result_cache
    if args.shared_cache is not None:
        os.environ["NEMO_RCACHE_SHARED"] = args.shared_cache
    if args.prewarm is not None:
        os.environ["NEMO_SERVE_PREWARM"] = args.prewarm
    # Serving knobs are env-carried too (the admission controller reads the
    # env on first access, which is after these writes).
    if args.max_inflight is not None:
        os.environ["NEMO_SERVE_INFLIGHT"] = str(args.max_inflight)
    if args.max_queue is not None:
        os.environ["NEMO_SERVE_QUEUE"] = str(args.max_queue)
    if args.drain_s is not None:
        os.environ["NEMO_SERVE_DRAIN_S"] = str(args.drain_s)
    from nemo_tpu.utils.jax_config import (
        PlatformUnavailableError,
        enable_compilation_cache,
        ensure_platform,
    )

    # The sidecar owns the accelerator; resolve the platform under a
    # watchdog so a tunnel outage degrades to a CPU sidecar (loudly) instead
    # of a server whose first RPC hangs forever (VERDICT r2 weak #3).  An
    # explicit --platform=tpu demand with no reachable device refuses to
    # start at all rather than serving CPU answers under a TPU flag.
    try:
        platform = ensure_platform(args.platform, log=lambda m: log.warning("platform", detail=m))
    except PlatformUnavailableError as e:
        log.error("platform.unavailable", error=str(e))
        return 2
    log.info("platform.resolved", platform=platform)
    enable_compilation_cache()
    # NEMO_TRACE=<file> makes the sidecar write its OWN Perfetto trace at
    # shutdown; traced clients additionally collect per-RPC spans in-band
    # either way (obs/trace.py).
    if obs_trace.configure_from_env() is not None:
        log.info("trace.enabled", path=obs.tracer().path)
    # Always-on flight recorder (NEMO_FLIGHT=off to disable): the ring
    # costs a tuple append per span; the first breaker trip / watchdog
    # escalation / shed burst dumps a Perfetto-loadable postmortem bundle
    # even though nobody had --trace on (obs/flight.py).
    if obs.flight.configure_from_env() is not None:
        log.info("flight.armed", dir=obs.flight.recorder().out_dir)
    if args.profiler_port:
        import jax

        jax.profiler.start_server(args.profiler_port)
        log.info("profiler.listening", port=args.profiler_port)
    metrics_httpd = None
    if args.metrics_port:
        from nemo_tpu.obs import promexp

        metrics_httpd, mport = promexp.start_http_server(
            args.metrics_port, health=_health_state
        )
        log.info("metrics.listening", port=mport, paths=["/metrics", "/healthz"])
    server, port = make_server(args.port, args.max_workers)
    server.start()
    _prewarm_async()
    ctl = serve.controller()
    # The admission capacity as a gauge: the router's autoscaler divides
    # fleet queue depth by summed capacity to get a utilization it can
    # threshold (serve/autoscale.py).
    obs.metrics.gauge("serve.capacity", float(ctl.max_inflight))
    log.info(
        "sidecar.listening", port=port, replica=_replica_id(),
        max_inflight=ctl.max_inflight, max_queue=ctl.max_queue,
        shared_cache=os.environ.get("NEMO_RCACHE_SHARED") or None,
    )
    # Graceful drain (ISSUE 8 satellite): SIGTERM refuses new admissions
    # (the admission controller's drain flag, which /healthz mirrors as
    # NOT_SERVING so load balancers stop routing here), lets in-flight
    # requests finish up to NEMO_SERVE_DRAIN_S, then exits 0 — where the
    # pre-serve sidecar died mid-request.
    import signal

    term = threading.Event()

    def _on_term(signum, frame):  # signal-safe: just flag and wake
        term.set()

    prev_handler = signal.signal(signal.SIGTERM, _on_term)
    try:
        # Poll the term flag rather than wait_for_termination: grpc's
        # timeout return value is version-ambiguous, and nothing else
        # stops this server (SIGINT raises KeyboardInterrupt through the
        # wait, landing in the finally like before).
        while not term.wait(0.5):
            pass
        drain_s = serve.admission.drain_seconds()
        log.info(
            "sidecar.drain_begin", drain_s=drain_s,
            inflight=ctl.inflight, queued=ctl.queued,
        )
        ctl.begin_drain()
        # Drain ORDER matters (ISSUE 9 satellite): wait for the admission
        # tier — in-flight tickets, queued waiters, AND live streams (an
        # AnalyzeDirStream's terminal `done` event must go out, not be
        # severed) — BEFORE asking grpc to stop.  New arrivals during the
        # wait still reach handlers and are refused by admission
        # (UNAVAILABLE), so nothing accumulates; grpc's own stop then only
        # has stragglers that ignored the drain window.
        drained = ctl.drain_wait(drain_s)
        stopped = server.stop(grace=5.0)
        stopped.wait(timeout=5.0)
        obs.metrics.inc("serve.drained" if drained else "serve.drain_timeout")
        log.info("sidecar.drained", clean=drained, inflight=ctl.inflight)
        return 0 if drained else 1
    finally:
        signal.signal(signal.SIGTERM, prev_handler)
        if metrics_httpd is not None:
            metrics_httpd.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
