"""The TPU sidecar: a gRPC service running the fused analysis step on device.

Architecture per SURVEY.md §7: the CLI/ETL process packs provenance into
integer arrays (natively, ingest/native.py) and streams them here; this
process owns the accelerator, jits the fused pipeline once per
(shapes, statics) signature, and streams results back.  Replaces the
reference's per-node/edge Bolt round-trips to Neo4j (SURVEY.md §3.1 hot
loop #1) with one RPC per chunk of thousands of runs.

grpcio is present in this environment but its codegen plugin is not, so the
service is registered through grpc's generic-handler API with the
protoc-generated message classes doing (de)serialization.

Run:  python -m nemo_tpu.service.server --port 50051
"""

from __future__ import annotations

import argparse
import logging
import time
from concurrent import futures

import grpc

from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb

SERVICE = "nemo.NemoAnalysis"
VERSION = "1"

log = logging.getLogger("nemo.sidecar")


class _Impl:
    """Method implementations; one fused-step jit cache per process."""

    def health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        devs = jax.devices()
        return pb.HealthResponse(
            platform=devs[0].platform, device_count=len(devs), version=VERSION
        )

    def _analyze_one(self, request: pb.AnalyzeRequest) -> pb.AnalyzeResponse:
        import jax

        from nemo_tpu.models.pipeline_model import analysis_step

        from nemo_tpu.backend.jax_backend import _pack_out_default, _unpack_summary

        pre = codec.batch_arrays_from_pb(request.pre)
        post = codec.batch_arrays_from_pb(request.post)
        static = codec.static_from_pb(request.static)
        t0 = time.perf_counter()
        # The server owns the device, so it decides the transfer folding
        # (like LocalExecutor.run): with pack_out the program's bool
        # outputs — including this path's diff tail — arrive as ONE
        # bit-packed device->host copy and unpack here, before the wire
        # codec (which bit-packs bools again for transport).  Clients are
        # unaffected; this static never comes from the request.
        static = dict(static, pack_out=bool(_pack_out_default()))
        out = analysis_step(pre, post, **static)
        out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        if "packed_summary" in out:
            out = dict(out)
            out.update(
                _unpack_summary(
                    out.pop("packed_summary"),
                    b=int(pre.is_goal.shape[0]),
                    v=int(static["v"]),
                    t=int(static["num_tables"]),
                    # Derive from the same dict used for dispatch so the
                    # packed layout and the unpack can never diverge if the
                    # codec ever starts carrying with_diff (ADVICE r4 #2).
                    with_diff=bool(static.get("with_diff", True)),
                )
            )
        return codec.outputs_to_pb(out, chunk=request.chunk, step_seconds=dt)

    def analyze(self, request: pb.AnalyzeRequest, context) -> pb.AnalyzeResponse:
        return self._analyze_one(request)

    def analyze_stream(self, request_iterator, context):
        # Sequential device dispatch preserves chunk arrival order; gRPC's
        # flow control provides the backpressure (SURVEY.md §7 hard part 6).
        for request in request_iterator:
            yield self._analyze_one(request)

    def kernel(self, request: pb.KernelRequest, context) -> pb.KernelResponse:
        """Named device-kernel dispatch for the ServiceBackend: the request's
        (verb, arrays, params) triple runs through the same LocalExecutor the
        in-process JaxBackend uses, so both deployments execute identical
        device code."""
        from nemo_tpu.backend.jax_backend import LocalExecutor

        verb, arrays, params = codec.kernel_request_from_pb(request)
        if verb not in LocalExecutor.VERBS:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel verb {verb!r}")
        t0 = time.perf_counter()
        try:
            # LocalExecutor is stateless; the jit caches live on the
            # module-level kernel functions.
            out = LocalExecutor().run(verb, arrays, params)
        except KeyError as ex:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"missing kernel input: {ex}")
        return codec.kernel_response_to_pb(out, step_seconds=time.perf_counter() - t0)


def make_server(port: int = 0, max_workers: int = 4) -> tuple[grpc.Server, int]:
    """Build (but don't start) the sidecar server; returns (server, port)."""
    impl = _Impl()
    handlers = {
        "Health": grpc.unary_unary_rpc_method_handler(
            impl.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
        "Analyze": grpc.unary_unary_rpc_method_handler(
            impl.analyze,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "AnalyzeStream": grpc.stream_stream_rpc_method_handler(
            impl.analyze_stream,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "Kernel": grpc.unary_unary_rpc_method_handler(
            impl.kernel,
            request_deserializer=pb.KernelRequest.FromString,
            response_serializer=pb.KernelResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 1 << 30),
            ("grpc.max_send_message_length", 1 << 30),
        ],
    )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nemo-tpu-sidecar")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument("--max-workers", type=int, default=4)
    parser.add_argument(
        "--profiler-port",
        type=int,
        default=0,
        help="start jax.profiler.start_server on this port so TensorBoard/"
        "xprof can capture device traces from the running sidecar (0 = off)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe under a watchdog, CPU fallback on "
        "tunnel outage), 'cpu', 'tpu', or a concrete platform name "
        "(default: $NEMO_PLATFORM or auto)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from nemo_tpu.utils.jax_config import (
        PlatformUnavailableError,
        enable_compilation_cache,
        ensure_platform,
    )

    # The sidecar owns the accelerator; resolve the platform under a
    # watchdog so a tunnel outage degrades to a CPU sidecar (loudly) instead
    # of a server whose first RPC hangs forever (VERDICT r2 weak #3).  An
    # explicit --platform=tpu demand with no reachable device refuses to
    # start at all rather than serving CPU answers under a TPU flag.
    try:
        platform = ensure_platform(args.platform, log=log.warning)
    except PlatformUnavailableError as e:
        log.error("fatal: %s", e)
        return 2
    log.info("jax platform: %s", platform)
    enable_compilation_cache()
    if args.profiler_port:
        import jax

        jax.profiler.start_server(args.profiler_port)
        log.info("jax profiler server on port %d", args.profiler_port)
    server, port = make_server(args.port, args.max_workers)
    server.start()
    log.info("sidecar listening on 127.0.0.1:%d", port)
    server.wait_for_termination()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
