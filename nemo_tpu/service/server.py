"""The TPU sidecar: a gRPC service running the fused analysis step on device.

Architecture per SURVEY.md §7: the CLI/ETL process packs provenance into
integer arrays (natively, ingest/native.py) and streams them here; this
process owns the accelerator, jits the fused pipeline once per
(shapes, statics) signature, and streams results back.  Replaces the
reference's per-node/edge Bolt round-trips to Neo4j (SURVEY.md §3.1 hot
loop #1) with one RPC per chunk of thousands of runs.

grpcio is present in this environment but its codegen plugin is not, so the
service is registered through grpc's generic-handler API with the
protoc-generated message classes doing (de)serialization.

Operational surface (ISSUE 4): `--metrics-port` / `NEMO_METRICS_PORT`
serves the obs metrics registry in Prometheus text format on a stdlib
http.server thread (`/metrics`, plus `/healthz` mirroring the gRPC Health
state) so a long-lived sidecar is scrapeable; every log line is a
structured JSON record (obs/log.py) carrying the client's propagated trace
id where one exists, and every RPC lands in a `serve.rpc_latency_s.<rpc>`
histogram.

Run:  python -m nemo_tpu.service.server --port 50051 --metrics-port 9464
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from concurrent import futures

import grpc

from nemo_tpu import obs
from nemo_tpu.obs import log as obs_log
from nemo_tpu.obs import trace as obs_trace
from nemo_tpu.service import codec
from nemo_tpu.service.proto import nemo_service_pb2 as pb

SERVICE = "nemo.NemoAnalysis"
VERSION = "1"

log = obs_log.get_logger("nemo.sidecar")


def _health_state() -> dict:
    """The `/healthz` document: a JSON mirror of the gRPC Health response
    (same fields a `health()` client sees), computed per request so an
    operator's curl reflects live device state."""
    import jax

    devs = jax.devices()
    return {
        "status": "SERVING",
        "platform": devs[0].platform,
        "device_count": len(devs),
        "version": VERSION,
    }


def _rpc_observed(name: str, t0: float, trace_id: str | None) -> None:
    """Per-RPC server-side accounting shared by every handler: the latency
    histogram the Prometheus endpoint exposes, plus a trace-correlated
    debug record (the log line that joins a scrape, a trace file, and a
    client's story under one id)."""
    dt = time.perf_counter() - t0
    obs.metrics.observe(f"serve.rpc_latency_s.{name}", dt)
    log.debug(
        "serve.rpc", rpc=name, seconds=round(dt, 6),
        trace_id=trace_id,
    )
    slow_ms = obs_log.slow_dispatch_ms()
    if slow_ms and dt * 1000.0 > slow_ms:
        obs.metrics.inc("watchdog.slow_rpc")
        log.warning(
            "serve.slow_rpc", rpc=name, wall_ms=round(dt * 1000.0, 1),
            threshold_ms=slow_ms, trace_id=trace_id,
        )


#: Traced requests sharing the lazily-created PATHLESS collector tracer.
#: When the count returns to zero the collector is torn down, so a
#: long-lived sidecar serving untraced traffic records no spans at all —
#: the collector exists only while a traced request is in flight.
_collector_lock = threading.Lock()
_collector_refs = [0]


class _SpanCollection:
    """Per-request span-collection state.

    A tracing client sends its trace id in 'nemo-trace-id' request
    metadata; the handler records its spans under that id and returns them
    in 'nemo-spans-bin' trailing metadata, which the client stitches into
    its own trace file — one Perfetto view, both processes.  Collection is
    best-effort: with several concurrently tracing clients, spans may ride
    home on the wrong response (they still belong to the same sidecar
    timeline); the metrics counters are exact regardless.

    Lifecycle: acquire on construction (lazily enabling a pathless
    collector tracer unless the operator set NEMO_TRACE — an operator's
    file tracer is only copied from, never drained), serialize with
    trailing(), and ALWAYS release() (handlers do it in a finally) so the
    pathless collector is torn down when the last traced request finishes.
    """

    #: One response's span payload cap.  gRPC refuses oversized metadata
    #: (make_server/RemoteAnalyzer raise grpc.max_metadata_size above
    #: this); a huge streamed corpus keeps its NEWEST spans.
    MAX_BYTES = 1 << 20

    def __init__(self, context) -> None:
        md = dict(context.invocation_metadata() or ())
        self.tid = md.get("nemo-trace-id")
        self._owned = False
        self._tracer = None
        self._mark = 0
        if self.tid is None:
            return
        with _collector_lock:
            t = obs.tracer()
            if t is None:
                t = obs_trace.start_trace(None)
            if not t.path:
                _collector_refs[0] += 1
                self._owned = True
            self._tracer = t
            self._mark = t.mark()

    def trailing(self) -> tuple:
        """Trailing-metadata entries carrying the spans this request
        recorded (capped at MAX_BYTES, oldest dropped first)."""
        t = self._tracer
        if t is None:
            return ()
        spans = t.spans_since(self._mark) if t.path else t.drain_spans()
        payload = b""
        while spans:
            payload = json.dumps(spans).encode("utf-8")
            if len(payload) <= self.MAX_BYTES:
                break
            # Keep the newest spans: for a streamed corpus they cover the
            # most recent chunks, and the client's own rpc span still
            # brackets the whole call.
            spans = spans[max(1, len(spans) // 4):]
        if not spans or len(payload) > self.MAX_BYTES:
            return ()
        return (("nemo-spans-bin", payload),)

    def release(self) -> None:
        if not self._owned:
            return
        self._owned = False
        with _collector_lock:
            _collector_refs[0] -= 1
            t = obs.tracer()
            if _collector_refs[0] == 0 and t is not None and not t.path:
                # finish() on a pathless tracer writes nothing — it just
                # disables collection until the next traced request.
                obs_trace.finish()


class _Impl:
    """Method implementations; one fused-step jit cache per process.

    Trace-context propagation is per request via _SpanCollection; every
    handler acquires one and releases it in a finally.
    """

    def health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        col = _SpanCollection(context)
        t0 = time.perf_counter()
        try:
            with obs.span("serve:Health", trace_id=col.tid):
                import jax

                devs = jax.devices()
                resp = pb.HealthResponse(
                    platform=devs[0].platform, device_count=len(devs), version=VERSION
                )
            # The metrics snapshot rides every Health response (trailing
            # metadata — no proto bump): operators inspect sidecar state
            # (dispatch counts, compile-cache hits, step latencies) through
            # any client's health() without SSH.
            context.set_trailing_metadata(
                (("nemo-metrics-bin", json.dumps(obs.metrics.snapshot()).encode("utf-8")),)
                + col.trailing()
            )
            return resp
        finally:
            _rpc_observed("Health", t0, col.tid)
            col.release()

    def _run_step(
        self, pre, post, static: dict, chunk: int, trace_id: str | None
    ) -> pb.AnalyzeResponse:
        """One fused analysis_step dispatch -> wire response; shared by the
        array-upload paths (Analyze/AnalyzeStream) and the server-side
        corpus path (AnalyzeDir)."""
        import jax

        from nemo_tpu.models.pipeline_model import analysis_step

        from nemo_tpu.backend.jax_backend import _pack_out_default, _unpack_summary

        b = int(pre.is_goal.shape[0])
        t0 = time.perf_counter()
        # The server owns the device, so it decides the transfer folding
        # (like LocalExecutor.run): with pack_out the program's bool
        # outputs — including this path's diff tail — arrive as ONE
        # bit-packed device->host copy and unpack here, before the wire
        # codec (which bit-packs bools again for transport).  Clients are
        # unaffected; this static never comes from the request.
        static = dict(static, pack_out=bool(_pack_out_default()))
        with obs.span("serve:analysis_step", chunk=chunk, rows=b, trace_id=trace_id):
            out = analysis_step(pre, post, **static)
            out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        obs.metrics.inc("serve.analyze_chunks")
        obs.metrics.observe("serve.step_s", dt)
        obs.metrics.observe("serve.batch_rows", b)
        if "packed_summary" in out:
            out = dict(out)
            out.update(
                _unpack_summary(
                    out.pop("packed_summary"),
                    b=b,
                    v=int(static["v"]),
                    t=int(static["num_tables"]),
                    # Derive from the same dict used for dispatch so the
                    # packed layout and the unpack can never diverge if the
                    # codec ever starts carrying with_diff (ADVICE r4 #2).
                    with_diff=bool(static.get("with_diff", True)),
                )
            )
        return codec.outputs_to_pb(out, chunk=chunk, step_seconds=dt)

    def _analyze_one(
        self, request: pb.AnalyzeRequest, trace_id: str | None = None
    ) -> pb.AnalyzeResponse:
        pre = codec.batch_arrays_from_pb(request.pre)
        post = codec.batch_arrays_from_pb(request.post)
        static = codec.static_from_pb(request.static)
        return self._run_step(pre, post, static, int(request.chunk), trace_id)

    def analyze(self, request: pb.AnalyzeRequest, context) -> pb.AnalyzeResponse:
        col = _SpanCollection(context)
        t0 = time.perf_counter()
        try:
            resp = self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return resp
        finally:
            _rpc_observed("Analyze", t0, col.tid)
            col.release()

    def analyze_stream(self, request_iterator, context):
        # Sequential device dispatch preserves chunk arrival order; gRPC's
        # flow control provides the backpressure (SURVEY.md §7 hard part 6).
        col = _SpanCollection(context)
        t0 = time.perf_counter()
        try:
            for request in request_iterator:
                yield self._analyze_one(request, trace_id=col.tid)
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
        finally:
            _rpc_observed("AnalyzeStream", t0, col.tid)
            col.release()

    def analyze_dir(self, request: dict, context) -> pb.AnalyzeResponse:
        """Server-side corpus analysis: the request names a Molly directory
        reachable from THIS process (the sidecar normally shares the host
        or a mounted corpus volume with its clients), so repeated client
        sessions over the same corpus skip both the array upload AND the
        JSON parse — the sidecar consults its own persistent corpus store
        (nemo_tpu/store, ``--corpus-cache``/``NEMO_CORPUS_CACHE``) and
        mmap-loads on every session after the first.

        Wire shape: the request is a JSON object (``{"dir": ..., optional
        "corpus_cache": ..., optional "result_cache": ...}``) carried
        through a generic-handler JSON deserializer — no protoc
        regeneration needed — and the response is the standard
        AnalyzeResponse the Analyze RPC returns.

        Response caching: when the sidecar's result cache resolves
        (``--result-cache``/``NEMO_RESULT_CACHE``) and the corpus was
        served by the store, the serialized response is cached
        content-addressed on (segment fingerprints, statics, wire
        version, analysis ABI) — a repeat session gets the stored bytes
        with ZERO device dispatches, flagged ``nemo-rcache: hit`` in the
        trailing metadata (hit/miss/off streams back on every call).
        ``result_cache`` in the request can only opt OUT ("off"), like
        ``corpus_cache``."""
        col = _SpanCollection(context)
        t0 = time.perf_counter()
        try:
            if not isinstance(request, dict):
                # Valid JSON but not an object ('[1]', '"x"') — the
                # deserializer accepted it; fail with the clear status, not
                # an AttributeError surfacing as UNKNOWN.
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "AnalyzeDir request must be a JSON object",
                )
            d = request.get("dir", "")
            if not d or not os.path.isdir(d):
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"not a directory on the sidecar host: {d!r}",
                )
            from nemo_tpu.analysis.pipeline import _ingest
            from nemo_tpu.models.pipeline_model import BatchArrays
            from nemo_tpu.store import corpus_cache_dir, resolve_store

            with obs.span(
                "serve:AnalyzeDir", dir=os.path.basename(d), trace_id=col.tid
            ):
                # Store authority is the OPERATOR's (--corpus-cache /
                # NEMO_CORPUS_CACHE): a client may opt OUT for its request
                # (corpus_cache="off"), but can never enable or redirect a
                # server-side store the operator disabled — the request
                # names a client-chosen server path a full corpus mirror
                # would be written to.
                req_cache = request.get("corpus_cache")
                client_opt_out = (
                    req_cache is not None and corpus_cache_dir(req_cache) is None
                )
                store = None if client_opt_out else resolve_store()
                # Warm array-only path first: the handler dispatches arrays
                # + statics, so a hit skips the per-run MollyOutput build.
                nc = store.load_corpus(d) if store is not None else None
                if nc is None:
                    # Cold/stale (already counted by load_corpus above):
                    # the pipeline's canonical parse+populate with a
                    # pre-parse snapshot — one policy, shared, not a
                    # server-side copy; consult_store=False so the miss is
                    # not probed and counted a second time.
                    molly = _ingest(d, use_packed=True, store=store, consult_store=False)
                    nc = getattr(molly, "native_corpus", None)
                if nc is not None:
                    from nemo_tpu.ingest.native import corpus_step_static

                    pre = BatchArrays.from_packed(nc.pre)
                    post = BatchArrays.from_packed(nc.post)
                    static = corpus_step_static(nc)
                    seg_meta = getattr(nc, "store_segments", None)
                else:  # object-loader fallback (no native lib, cold store)
                    from nemo_tpu.models.pipeline_model import pack_molly_for_step

                    pre, post, static = pack_molly_for_step(molly)
                    seg_meta = getattr(molly, "store_segments", None)
                obs.metrics.inc("serve.analyze_dir")

                # Response cache: operator authority like the store —
                # resolved from the sidecar's own env, request can only
                # opt out.  Keyed on segment fingerprints + statics + wire
                # version, so a stale store or a kernel ABI bump can never
                # serve old bytes.
                from nemo_tpu.analysis.delta import blob_cache_key
                from nemo_tpu.store.rcache import (
                    resolve_result_cache,
                    result_cache_dir,
                )

                req_rc = request.get("result_cache")
                rc_opt_out = req_rc is not None and result_cache_dir(req_rc) is None
                rc = None if rc_opt_out else resolve_result_cache()
                blob_key = (
                    blob_cache_key(
                        "analyze_dir",
                        seg_meta,
                        {"static": {k: int(v) for k, v in static.items()}, "wire": VERSION},
                    )
                    if rc is not None
                    else None
                )
                rc_status = "off"
                resp = None
                if blob_key is not None:
                    payload = rc.load_blob("analyze_dir", blob_key)
                    if payload is not None:
                        resp = pb.AnalyzeResponse.FromString(payload)
                        # The stored wall is the POPULATING run's; a served
                        # hit dispatched nothing.
                        resp.step_seconds = 0.0
                        rc_status = "hit"
                        obs.metrics.inc("serve.analyze_dir_cached")
                    else:
                        rc_status = "miss"
                if resp is None:
                    resp = self._run_step(pre, post, static, chunk=0, trace_id=col.tid)
                    if blob_key is not None:
                        rc.put_blob("analyze_dir", blob_key, resp.SerializeToString())
            md = col.trailing() + (("nemo-rcache", rc_status),)
            context.set_trailing_metadata(md)
            return resp
        finally:
            _rpc_observed("AnalyzeDir", t0, col.tid)
            col.release()

    def kernel(self, request: pb.KernelRequest, context) -> pb.KernelResponse:
        """Named device-kernel dispatch for the ServiceBackend: the request's
        (verb, arrays, params) triple runs through the same LocalExecutor the
        in-process JaxBackend uses, so both deployments execute identical
        device code."""
        from nemo_tpu.backend.jax_backend import LocalExecutor

        col = _SpanCollection(context)
        t_rpc = time.perf_counter()
        try:
            verb, arrays, params = codec.kernel_request_from_pb(request)
            if verb not in LocalExecutor.VERBS:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"unknown kernel verb {verb!r}")
            t0 = time.perf_counter()
            try:
                # LocalExecutor is stateless; the jit caches live on the
                # module-level kernel functions.  Its own kernel:<verb> span
                # rides home in the trailing metadata.
                with obs.span("serve:Kernel", verb=verb, trace_id=col.tid):
                    out = LocalExecutor().run(verb, arrays, params)
            except KeyError as ex:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"missing kernel input: {ex}")
            obs.metrics.inc("serve.kernel_calls")
            md = col.trailing()
            if md:
                context.set_trailing_metadata(md)
            return codec.kernel_response_to_pb(out, step_seconds=time.perf_counter() - t0)
        finally:
            _rpc_observed("Kernel", t_rpc, col.tid)
            col.release()


def make_server(port: int = 0, max_workers: int = 4) -> tuple[grpc.Server, int]:
    """Build (but don't start) the sidecar server; returns (server, port)."""
    impl = _Impl()
    handlers = {
        "Health": grpc.unary_unary_rpc_method_handler(
            impl.health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
        "Analyze": grpc.unary_unary_rpc_method_handler(
            impl.analyze,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "AnalyzeStream": grpc.stream_stream_rpc_method_handler(
            impl.analyze_stream,
            request_deserializer=pb.AnalyzeRequest.FromString,
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        # JSON-carried request (generic handlers accept any serializer, so
        # no protoc regeneration is needed for the path-only payload).
        "AnalyzeDir": grpc.unary_unary_rpc_method_handler(
            impl.analyze_dir,
            request_deserializer=lambda b: json.loads(b.decode("utf-8")),
            response_serializer=pb.AnalyzeResponse.SerializeToString,
        ),
        "Kernel": grpc.unary_unary_rpc_method_handler(
            impl.kernel,
            request_deserializer=pb.KernelRequest.FromString,
            response_serializer=pb.KernelResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_receive_message_length", 1 << 30),
            ("grpc.max_send_message_length", 1 << 30),
            # Span trailing metadata (traced clients) can reach
            # _SpanCollection.MAX_BYTES; default metadata limits are 8 KB.
            ("grpc.max_metadata_size", 2 << 20),
        ],
    )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE, handlers),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nemo-tpu-sidecar")
    parser.add_argument("--port", type=int, default=50051)
    parser.add_argument("--max-workers", type=int, default=4)
    parser.add_argument(
        "--profiler-port",
        type=int,
        default=0,
        help="start jax.profiler.start_server on this port so TensorBoard/"
        "xprof can capture device traces from the running sidecar (0 = off)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        metavar="NAME",
        help="jax platform: 'auto' (probe under a watchdog, CPU fallback on "
        "tunnel outage), 'cpu', 'tpu', or a concrete platform name "
        "(default: $NEMO_PLATFORM or auto)",
    )
    def _metrics_port_default() -> int:
        # Junk env warns-and-defaults to off, like every observability
        # knob: a typo here must not keep the gRPC service itself down.
        try:
            return int(os.environ.get("NEMO_METRICS_PORT", "0") or 0)
        except ValueError:
            log.warning(
                "metrics.bad_port_env",
                value=os.environ.get("NEMO_METRICS_PORT"),
                detail="NEMO_METRICS_PORT is not an integer; metrics port off",
            )
            return 0

    parser.add_argument(
        "--corpus-cache",
        default=None,
        metavar="DIR|off",
        help="server-side persistent corpus store root consulted by the "
        "AnalyzeDir RPC (default $NEMO_CORPUS_CACHE or "
        "~/.cache/nemo_tpu/corpus; 'off' disables): repeated client "
        "sessions over the same corpus directory skip upload AND parse",
    )
    parser.add_argument(
        "--result-cache",
        default=None,
        metavar="DIR|off",
        help="server-side analysis result cache consulted by the AnalyzeDir "
        "RPC (default $NEMO_RESULT_CACHE or ~/.cache/nemo_tpu/results; "
        "'off' disables): a repeat session over an unchanged stored corpus "
        "gets the cached response bytes with zero device dispatches "
        "(trailing metadata nemo-rcache: hit)",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=_metrics_port_default(),
        help="serve Prometheus text-format metrics on http://127.0.0.1:PORT"
        "/metrics (plus /healthz mirroring the gRPC Health state) from a "
        "stdlib http.server thread; 0 disables (default: "
        "$NEMO_METRICS_PORT or off)",
    )
    args = parser.parse_args(argv)
    if args.corpus_cache is not None:
        # Env-carried like the CLI's knob, so the AnalyzeDir handler and the
        # store module resolve identically in every process shape.
        os.environ["NEMO_CORPUS_CACHE"] = args.corpus_cache
    if args.result_cache is not None:
        os.environ["NEMO_RESULT_CACHE"] = args.result_cache
    from nemo_tpu.utils.jax_config import (
        PlatformUnavailableError,
        enable_compilation_cache,
        ensure_platform,
    )

    # The sidecar owns the accelerator; resolve the platform under a
    # watchdog so a tunnel outage degrades to a CPU sidecar (loudly) instead
    # of a server whose first RPC hangs forever (VERDICT r2 weak #3).  An
    # explicit --platform=tpu demand with no reachable device refuses to
    # start at all rather than serving CPU answers under a TPU flag.
    try:
        platform = ensure_platform(args.platform, log=lambda m: log.warning("platform", detail=m))
    except PlatformUnavailableError as e:
        log.error("platform.unavailable", error=str(e))
        return 2
    log.info("platform.resolved", platform=platform)
    enable_compilation_cache()
    # NEMO_TRACE=<file> makes the sidecar write its OWN Perfetto trace at
    # shutdown; traced clients additionally collect per-RPC spans in-band
    # either way (obs/trace.py).
    if obs_trace.configure_from_env() is not None:
        log.info("trace.enabled", path=obs.tracer().path)
    if args.profiler_port:
        import jax

        jax.profiler.start_server(args.profiler_port)
        log.info("profiler.listening", port=args.profiler_port)
    metrics_httpd = None
    if args.metrics_port:
        from nemo_tpu.obs import promexp

        metrics_httpd, mport = promexp.start_http_server(
            args.metrics_port, health=_health_state
        )
        log.info("metrics.listening", port=mport, paths=["/metrics", "/healthz"])
    server, port = make_server(args.port, args.max_workers)
    server.start()
    log.info("sidecar.listening", port=port)
    try:
        server.wait_for_termination()
    finally:
        if metrics_httpd is not None:
            metrics_httpd.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
