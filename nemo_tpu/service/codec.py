"""numpy/jax <-> protobuf conversion for the sidecar wire protocol."""

from __future__ import annotations

import numpy as np

from nemo_tpu.service.proto import nemo_service_pb2 as pb

_COND_FIELDS = (
    "table_id",
    "label_id",
    "type_id",
    "is_goal",
    "node_mask",
    "edge_src",
    "edge_dst",
    "edge_mask",
)


# Boolean arrays dominate the wire (masks, adjacencies, kernel outputs);
# packbits cuts their bytes 8x.  The marker is a dtype tag, so mixed-version
# peers fail loudly on decode rather than misread data.
_PACKED_BOOL = "packedbool"


def ndarray_to_pb(a) -> pb.NdArray:
    a = np.ascontiguousarray(np.asarray(a))
    if a.dtype == np.bool_:
        return pb.NdArray(
            dtype=_PACKED_BOOL, shape=list(a.shape), data=np.packbits(a).tobytes()
        )
    return pb.NdArray(dtype=str(a.dtype), shape=list(a.shape), data=a.tobytes())


def ndarray_from_pb(m: pb.NdArray, copy: bool = False) -> np.ndarray:
    """Decode to numpy; zero-copy (read-only view) by default — the
    device-bound path hands this straight to jnp.asarray."""
    shape = tuple(m.shape)
    if m.dtype == _PACKED_BOOL:
        n = int(np.prod(shape, dtype=np.int64))
        bits = np.unpackbits(np.frombuffer(m.data, dtype=np.uint8), count=n)
        return bits.astype(bool).reshape(shape)
    a = np.frombuffer(m.data, dtype=np.dtype(m.dtype)).reshape(shape)
    return a.copy() if copy else a


def batch_arrays_to_pb(arrays) -> pb.CondBatch:
    """BatchArrays (or any object with the 8 packed fields) -> CondBatch."""
    return pb.CondBatch(**{f: ndarray_to_pb(getattr(arrays, f)) for f in _COND_FIELDS})


def batch_arrays_from_pb(m: pb.CondBatch):
    from nemo_tpu.models.pipeline_model import BatchArrays
    import jax.numpy as jnp

    return BatchArrays(**{f: jnp.asarray(ndarray_from_pb(getattr(m, f))) for f in _COND_FIELDS})


def static_to_pb(static: dict) -> pb.StaticParams:
    return pb.StaticParams(**{k: int(v) for k, v in static.items()})


def static_from_pb(m: pb.StaticParams) -> dict:
    return dict(
        v=int(m.v),
        pre_tid=int(m.pre_tid),
        post_tid=int(m.post_tid),
        num_tables=int(m.num_tables),
        num_labels=int(m.num_labels),
        max_depth=int(m.max_depth),
        comp_linear=bool(m.comp_linear),
    )


def kernel_request_to_pb(verb: str, arrays: dict, params: dict) -> pb.KernelRequest:
    req = pb.KernelRequest(verb=verb)
    for k, v in arrays.items():
        req.arrays[k].CopyFrom(ndarray_to_pb(v))
    for k, v in params.items():
        req.params[k] = int(v)
    return req


def kernel_request_from_pb(m: pb.KernelRequest) -> tuple[str, dict, dict]:
    arrays = {k: ndarray_from_pb(v) for k, v in m.arrays.items()}
    params = {k: int(v) for k, v in m.params.items()}
    return m.verb, arrays, params


def kernel_response_to_pb(outputs: dict, step_seconds: float) -> pb.KernelResponse:
    resp = pb.KernelResponse(step_seconds=step_seconds)
    for k, v in outputs.items():
        resp.outputs[k].CopyFrom(ndarray_to_pb(v))
    return resp


def kernel_response_from_pb(m: pb.KernelResponse) -> dict[str, np.ndarray]:
    return {k: ndarray_from_pb(v) for k, v in m.outputs.items()}


def outputs_to_pb(outputs: dict, chunk: int, step_seconds: float) -> pb.AnalyzeResponse:
    resp = pb.AnalyzeResponse(chunk=chunk, step_seconds=step_seconds)
    for k, v in outputs.items():
        resp.outputs[k].CopyFrom(ndarray_to_pb(v))
    return resp


def outputs_from_pb(m: pb.AnalyzeResponse) -> dict[str, np.ndarray]:
    return {k: ndarray_from_pb(v) for k, v in m.outputs.items()}
