"""The query planner: lower a validated AST onto the batched kernel family.

A :class:`QueryPlan` is the symbolic lowering — per pattern, a conjunction
of vectorized plane comparisons per step plus the hop sequence, with the
derived-plane requirements (condition-holds, the time plane) hoisted out so
the executor materializes each at most once per bucket.  Binding resolves
predicate NAMES to vocabulary ids against the corpus vocab: the plan stays
name-keyed (cacheable across corpora), the bound form is a flat hashable
tuple — the jit-static argument of the device evaluator, so one compiled
program serves every same-shape bucket of every query with the same bound
structure.

Pattern evaluation is the standard forward/backward chain intersection on
the EXISTING frontier primitives (ops/sparse_device.py ``_push_any`` /
``_reach_any``; ops/sparse_host.py ``scat_any`` / ``bfs_any``):

    f[0]   = mask(step 0)
    f[i]   = mask(step i) & hop_fwd(f[i-1])     (one wave, or >=1-hop reach)
    b[k]   = mask(step k)
    b[i]   = mask(step i) & hop_bwd(b[i+1])     (same wave, edges reversed)
    capture= f[ci] & b[ci]

``f[i] & b[i]`` is exact for chains: forward support proves a prefix path
into the node, backward support proves a suffix path out of it, and their
concatenation is a full match (predicates are node-local).  The query's
capture set is the union over patterns — node-set semantics, which is what
makes every aggregation an order-insensitive reduce.
"""

from __future__ import annotations

from dataclasses import dataclass

from nemo_tpu.query.lang import HOP_ADJ, FIELDS, Query, QueryError

#: type-name -> packed type id (graphs/packed.py _TYPE_IDS — kept in sync by
#: tests/test_query.py's lowering units).
_TYPE_IDS = {"": 0, "async": 1, "next": 2, "collapsed": 3}

#: Bound-predicate sentinel for a name NO run in the bound segment interned:
#: planes hold ids >= -1 (-1 = padding), so -2 never compares equal.  The
#: corpus-level loud unknown-name check happens before binding
#: (:meth:`QueryPlan.validate_names`); the sentinel covers names that exist
#: in the corpus but not in one segment's vocabulary.
_NO_ID = -2

_NAME_VOCABS = {"table": "tables", "label": "labels", "time": "times"}


@dataclass(frozen=True)
class PatternPlan:
    """One lowered chain: per-step test tuples + hops + capture index."""

    #: per step: tuple of atomic tests, each ("kind", k) / (field, op, value)
    steps: tuple
    hops: tuple
    capture: int


@dataclass(frozen=True)
class QueryPlan:
    """The symbolic plan: name-keyed, hashable, content-addressed."""

    graph: str
    cond_tid: int  # pinned condition table id: "pre"=0 / "post"=1
    run_filter: str
    agg: str
    needs_holds: bool
    needs_time: bool
    patterns: tuple
    key: str  # == Query.ast_hash() — the plan is a pure function of the AST

    # -- binding ----------------------------------------------------------
    def names(self) -> dict:
        """Vocabulary names the plan references, per name-valued field."""
        out: dict = {f: set() for f in _NAME_VOCABS}
        for p in self.patterns:
            for step in p.steps:
                for test in step:
                    if test[0] in _NAME_VOCABS:
                        out[test[0]].add(test[2])
        return out

    def validate_names(self, vocab) -> None:
        """Loud corpus-level resolution check (the fail-fast half of the
        env-knob policy): a name no run in the corpus ever interned is a
        typo, not an empty result."""
        for fld, wanted in self.names().items():
            voc = getattr(vocab, _NAME_VOCABS[fld])
            for name in sorted(wanted):
                if voc.lookup(name) < 0:
                    raise QueryError(
                        f"unknown {fld} {name!r}: no run in this corpus "
                        f"defines it (vocabulary has {len(voc)} {fld}s)"
                    )

    def bind(self, vocab) -> tuple:
        """Resolve names -> ids against one vocabulary.  Returns the flat
        hashable bound form the evaluators take as a jit-static:
        ``(patterns, needs_holds, cond_tid)`` with every test an
        ``(plane, op, int)`` triple."""
        def bind_test(test: tuple) -> tuple:
            if test[0] == "kind":
                return test
            fld, op, val = test
            if fld in _NAME_VOCABS:
                vid = getattr(vocab, _NAME_VOCABS[fld]).lookup(val)
                return (fld, op, int(vid) if vid >= 0 else _NO_ID)
            if fld == "type":
                return (fld, op, _TYPE_IDS[val])
            return (fld, op, bool(val))  # holds

        pats = tuple(
            (
                tuple(tuple(bind_test(t) for t in step) for step in p.steps),
                p.hops,
                p.capture,
            )
            for p in self.patterns
        )
        return (pats, self.needs_holds, self.cond_tid)

    # -- introspection ----------------------------------------------------
    def describe(self) -> list[str]:
        """The lowered kernel sequence, one line per primitive — what the
        planner unit tests assert pattern -> kernel lowering against."""
        out = [f"select graph={self.graph} runs={self.run_filter}"]
        if self.needs_holds:
            out.append(f"condition_holds tid={self.cond_tid}")
        for pi, p in enumerate(self.patterns):
            for si, step in enumerate(p.steps):
                tests = " & ".join(
                    f"kind={t[1]!r}" if t[0] == "kind"
                    else f"{t[0]}{t[1]}{t[2]!r}"
                    for t in step
                )
                out.append(f"p{pi} mask s{si}: {tests}")
            for hi, hop in enumerate(p.hops):
                kern = "push_any" if hop == HOP_ADJ else "reach_any"
                out.append(f"p{pi} fwd {kern} s{hi}->s{hi + 1}")
            for hi in range(len(p.hops) - 1, -1, -1):
                kern = "push_any" if p.hops[hi] == HOP_ADJ else "reach_any"
                out.append(f"p{pi} bwd {kern} s{hi + 1}->s{hi}")
            out.append(f"p{pi} capture s{p.capture}: fwd & bwd")
        out.append(f"reduce {self.agg}")
        return out


def plan_query(q: Query) -> QueryPlan:
    """Lower a validated query to its plan.  Pure AST function — the plan
    key IS the AST hash, so plan caching rides the query content address."""
    from nemo_tpu import obs

    q.validate()
    needs_holds = False
    needs_time = False
    pats = []
    for p in q.patterns:
        steps = []
        for s in p.steps:
            tests: list = [("kind", s.kind)]
            for pred in s.preds:
                if pred.field == "holds":
                    needs_holds = True
                if pred.field == "time":
                    needs_time = True
                tests.append((pred.field, pred.op, pred.value))
            steps.append(tuple(tests))
        pats.append(
            PatternPlan(steps=tuple(steps), hops=p.hops, capture=p.capture_index)
        )
    plan = QueryPlan(
        graph=q.graph,
        cond_tid=0 if q.graph == "pre" else 1,  # CorpusVocab pins pre=0/post=1
        run_filter=q.run_filter,
        agg=q.agg,
        needs_holds=needs_holds,
        needs_time=needs_time,
        patterns=tuple(pats),
        key=q.ast_hash(),
    )
    obs.metrics.inc("query.plans")
    return plan


# ---------------------------------------------------------------------------
# lowering sanity: every field the language admits has a lowering here
# ---------------------------------------------------------------------------
assert set(_NAME_VOCABS) | {"type", "holds"} == set(FIELDS)
