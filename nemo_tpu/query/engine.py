"""The query executor: segments -> scheduler Jobs -> order-insensitive reduce.

Execution reuses the whole production spine, not a private path:

* runs pack into the SAME power-of-two (V, E) buckets as the analysis
  verbs (graphs/packed.py:bucketize), so compiled programs are shared
  corpus-to-corpus;
* each bucket becomes a ``parallel/sched.py`` :class:`Job` with the new
  ``query`` verb class and ``lanes=("sparse_device", "host")`` — two
  bit-identical evaluators over the same bound plan, so cost-model
  routing, work stealing, dispatch deadlines, host failover and the
  device circuit breaker all apply unchanged;
* per-segment results are :class:`QueryPartial`\\ s — iteration-keyed plain
  data with a commutative/associative merge, the ``SegmentPartial``
  contract (analysis/delta.py) — cached per segment and as a full-result
  blob in the result cache, content-addressed on (query AST hash, segment
  fingerprints, analysis ABI) via ``blob_cache_key``.  A warm repeat is a
  zero-kernel-dispatch rcache hit, exactly like a verb.

Dispatch accounting: every bucket execution counts one
``kernel.dispatches.query`` so ``kernel_dispatch_count`` (the zero-dispatch
cache-hit assertion every smoke uses) covers the query engine too.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from nemo_tpu import obs
from nemo_tpu.query.lang import HOP_ADJ, Query, QueryError
from nemo_tpu.query.plan import QueryPlan, plan_query


# ---------------------------------------------------------------------------
# the serializable intermediate
# ---------------------------------------------------------------------------


@dataclass
class QueryPartial:
    """One segment's slice of a query result: iteration-keyed plain data
    (names, not vocab ids), JSON-serializable, merged order-insensitively.
    ``per_run`` values depend on the aggregation — list[str] (tables),
    int (count), bool (runs), dict[str, int] (count_by_table)."""

    per_run: dict = field(default_factory=dict)  # iteration -> value
    n_runs: int = 0

    def to_json(self) -> dict:
        return {
            "per_run": {str(k): v for k, v in self.per_run.items()},
            "n_runs": self.n_runs,
        }

    @classmethod
    def from_json(cls, d: dict) -> "QueryPartial":
        return cls(
            per_run={int(k): v for k, v in d["per_run"].items()},
            n_runs=int(d["n_runs"]),
        )


def merge_query_partials(parts: list) -> QueryPartial:
    """Commutative/associative merge: segments own disjoint iteration sets,
    so the union is order-insensitive (asserted under permutation by
    tests/test_query.py)."""
    out = QueryPartial()
    for p in parts:
        out.per_run.update(p.per_run)
        out.n_runs += p.n_runs
    return out


def finalize(plan: QueryPlan, merged: QueryPartial) -> dict:
    """Partial -> the result document.  Every rollup is computed from the
    iteration-keyed map in sorted-key order, so the document bytes are a
    pure function of content (cacheable byte-identically)."""
    runs = {str(k): merged.per_run[k] for k in sorted(merged.per_run)}
    doc: dict = {"agg": plan.agg, "graph": plan.graph, "n_runs": merged.n_runs}
    if plan.agg == "tables":
        doc["runs"] = runs
        doc["distinct"] = sorted({t for v in merged.per_run.values() for t in v})
    elif plan.agg == "count":
        doc["runs"] = runs
        doc["total"] = int(sum(merged.per_run.values()))
    elif plan.agg == "runs":
        doc["runs"] = sorted(k for k, v in merged.per_run.items() if v)
    else:  # count_by_table
        hist: dict = {}
        for v in merged.per_run.values():
            for t, n in v.items():
                hist[t] = hist.get(t, 0) + int(n)
        doc["by_table"] = {t: hist[t] for t in sorted(hist)}
        doc["total"] = int(sum(hist.values()))
    return doc


# ---------------------------------------------------------------------------
# the two lane evaluators (bit-identical over one bound plan)
# ---------------------------------------------------------------------------


def _eval_device(batch, time_plane, bound, num_tables: int):
    """Device lane: one jitted program per (bound plan, bucket shape) —
    plane compares + ``_push_any``/``_reach_any`` waves, vmapped over the
    run axis by construction ([B, V]/[B, E] planes)."""
    import jax

    from nemo_tpu.ops.sparse_device import resolve_wave_impl

    out = _device_eval_jit(
        np.asarray(batch.is_goal),
        np.asarray(batch.node_mask),
        np.asarray(batch.table_id),
        np.asarray(batch.label_id),
        time_plane,
        np.asarray(batch.type_id),
        np.asarray(batch.edge_src),
        np.asarray(batch.edge_dst),
        np.asarray(batch.edge_mask),
        spec=bound,
        v=batch.v,
        num_tables=num_tables,
        wave_impl=resolve_wave_impl(),
        interpret=jax.default_backend() != "tpu",
    )
    return np.asarray(out)


def _step_mask(planes: dict, step: tuple, xp):
    """One step's node mask: the kind constraint & every plane compare.
    Shared by both lanes (xp = jnp or np) so the boolean algebra cannot
    drift between them.  The ``holds`` plane is true only on holding GOAL
    nodes, and ``holds`` predicates only validate on goal steps (lang.py),
    so the negated form needs no extra goal guard."""
    is_goal = planes["is_goal"]
    m = planes["node_mask"]
    for test in step:
        if test[0] == "kind":
            if test[1] == "goal":
                m = m & is_goal
            elif test[1] == "rule":
                m = m & ~is_goal
            continue
        fld, op, val = test
        if fld == "holds":
            want = bool(val) if op == "=" else not val
            m = (m & planes["holds"]) if want else (m & ~planes["holds"])
            continue
        plane = planes[fld]
        m = (m & (plane == val)) if op == "=" else (m & (plane != val))
    return m


def _eval_patterns(planes: dict, patterns: tuple, hop, zeros, xp):
    """The forward/backward chain intersection, shared by both lanes:
    ``hop(state, kind, fwd)`` is the lane's wave primitive."""
    cap = zeros
    for steps, hops, ci in patterns:
        masks = [_step_mask(planes, s, xp) for s in steps]
        fwd = [masks[0]]
        for i, h in enumerate(hops):
            fwd.append(masks[i + 1] & hop(fwd[i], h, True))
        bwd = masks[-1]
        for i in range(len(hops) - 1, ci - 1, -1):
            bwd = masks[i] & hop(bwd, hops[i], False)
        cap = cap | (fwd[ci] & bwd)
    return cap


def _device_eval_impl(
    is_goal, node_mask, table_id, label_id, time_id, type_id,
    edge_src, edge_dst, edge_mask,
    spec: tuple, v: int, num_tables: int, wave_impl: str, interpret: bool,
):
    import jax.numpy as jnp

    from nemo_tpu.ops.sparse_device import (
        _condition_holds, _push_any, _reach_any,
    )

    patterns, needs_holds, cond_tid = spec
    planes = {
        "is_goal": is_goal, "node_mask": node_mask, "table": table_id,
        "label": label_id, "time": time_id, "type": type_id,
    }
    if needs_holds:
        ba = _BatchPlanes(
            is_goal=is_goal, node_mask=node_mask, table_id=table_id,
            edge_src=edge_src, edge_dst=edge_dst, edge_mask=edge_mask,
        )
        planes["holds"] = _condition_holds(ba, cond_tid, num_tables, v)

    def hop(state, kind, fwd: bool):
        src = edge_src if fwd else edge_dst
        dst = edge_dst if fwd else edge_src
        if kind == HOP_ADJ:
            return _push_any(state, src, dst, edge_mask, v)
        return _reach_any(state, src, dst, edge_mask, v, wave_impl, interpret)

    zeros = jnp.zeros(is_goal.shape, dtype=bool)
    return _eval_patterns(planes, patterns, hop, zeros, jnp)


class _BatchPlanes(NamedTuple):
    """The edge/node planes ``_condition_holds`` reads, as a jit-traceable
    pytree (the verb path hands it a full BatchArrays; the query path only
    has the planes)."""

    is_goal: object
    node_mask: object
    table_id: object
    edge_src: object
    edge_dst: object
    edge_mask: object


_DEVICE_EVAL_JIT: list = []


def _device_eval_jit(*args, **kw):
    """Lazily-jitted device evaluator: one compiled program per (bound
    plan, bucket shape) — the bound spec and shapes are jit-statics."""
    if not _DEVICE_EVAL_JIT:
        import jax

        _DEVICE_EVAL_JIT.append(
            jax.jit(
                _device_eval_impl,
                static_argnames=("spec", "v", "num_tables", "wave_impl", "interpret"),
            )
        )
    return _DEVICE_EVAL_JIT[0](*args, **kw)


def _eval_host(batch, time_plane, bound, num_tables: int):
    """Host lane: the same boolean algebra over the flat-scatter CSR prep
    (ops/sparse_host.py) — ``scat_any`` waves and ``bfs_any`` fix points.
    Bit-identical to the device lane (asserted by tests/test_query.py)."""
    from nemo_tpu.ops.sparse_host import _CondCSR, _condition_holds, bfs_any, build_csr

    csr = _CondCSR(batch)
    patterns, needs_holds, cond_tid = bound
    planes = {
        "is_goal": csr.is_goal, "node_mask": csr.node_mask, "table": csr.table,
        "label": np.asarray(batch.label_id, dtype=np.int64),
        "time": np.asarray(time_plane, dtype=np.int64),
        "type": csr.type_id,
    }
    if needs_holds:
        planes["holds"] = _condition_holds(csr, cond_tid, num_tables)

    csrs: dict = {}

    def hop(state, kind, fwd: bool):
        at, frm = (csr.dst, csr.src) if fwd else (csr.src, csr.dst)
        if kind == HOP_ADJ:
            return csr.scat_any(at, state.ravel()[frm])
        if fwd not in csrs:
            csrs[fwd] = build_csr(frm, at, csr.n)
        indptr, nbr = csrs[fwd]
        return bfs_any(indptr, nbr, state.ravel()).reshape(csr.b, csr.v)

    zeros = np.zeros((csr.b, csr.v), dtype=bool)
    return _eval_patterns(planes, patterns, hop, zeros, np)


# ---------------------------------------------------------------------------
# map / extract
# ---------------------------------------------------------------------------


def _time_plane(batch) -> np.ndarray:
    """[B, V] time-id plane (PackedBatch carries it only per graph)."""
    out = np.full((len(batch.n_nodes), batch.v), -1, dtype=np.int32)
    for i, g in enumerate(batch.graphs):
        out[i, : g.n_nodes] = g.time_id
    return out


def _extract(plan: QueryPlan, batch, cap: np.ndarray, vocab) -> dict:
    """Capture mask -> per-run plain-data values (names via the vocab)."""
    table = np.asarray(batch.table_id)
    out: dict = {}
    for i, rid in enumerate(batch.run_ids):
        m = cap[i]
        if plan.agg == "tables":
            out[rid] = sorted(
                {vocab.tables[t] for t in np.unique(table[i][m]) if t >= 0}
            )
        elif plan.agg == "count":
            out[rid] = int(m.sum())
        elif plan.agg == "runs":
            out[rid] = bool(m.any())
        else:  # count_by_table
            tids, counts = np.unique(table[i][m & (table[i] >= 0)], return_counts=True)
            out[rid] = {vocab.tables[t]: int(n) for t, n in zip(tids, counts)}
    return out


def _filter_runs(runs: list, run_filter: str) -> list:
    if run_filter == "failed":
        return [r for r in runs if not r.succeeded]
    if run_filter == "success":
        return [r for r in runs if r.succeeded]
    return list(runs)


def _empty_value(agg: str):
    """The aggregation value of a run with no captures (including runs whose
    provenance is absent — total replication failures have no post graph):
    present in the per-run map on EVERY lane and ingest path, so the object
    and packed-first paths produce identical documents."""
    return {"tables": [], "count": 0, "runs": False, "count_by_table": {}}[agg]


def map_segment_runs(
    plan: QueryPlan, runs: list, vocab, serial: bool = False, graph_of=None
) -> QueryPartial:
    """Map one segment's runs through the scheduler: bucketize, one Job per
    bucket (verb="query"), drain on the heterogeneous scheduler.

    ``graph_of(run) -> PackedGraph | None`` overrides graph materialization
    — the packed-first ingest path (ingest/native.py RawProv) supplies lazy
    array views over the native corpus instead of object repacks."""
    from nemo_tpu.graphs.packed import pack_graph
    from nemo_tpu.parallel.sched import HeterogeneousScheduler, Job

    from nemo_tpu.graphs.packed import bucketize

    selected = _filter_runs(runs, plan.run_filter)
    part = QueryPartial(n_runs=len(selected))
    if graph_of is None:
        prov_of = (
            (lambda r: r.pre_prov) if plan.graph == "pre" else (lambda r: r.post_prov)
        )

        def graph_of(r):
            prov = prov_of(r)
            return None if prov is None else pack_graph(prov, vocab)

    rids, graphs, empty_rids = [], [], []
    for r in selected:
        g = graph_of(r)
        if g is None or g.n_nodes == 0:
            empty_rids.append(r.iteration)
            continue
        rids.append(r.iteration)
        graphs.append(g)
    part.per_run = {rid: _empty_value(plan.agg) for rid in empty_rids}
    if not rids:
        return part

    batches = bucketize(rids, graphs)
    bound = plan.bind(vocab)
    num_tables = max(1, len(vocab.tables))
    results: dict = part.per_run

    def make_execute(batch):
        def execute(lane: str, reason: str, stolen: bool) -> dict:
            obs.metrics.inc("kernel.dispatches.query")
            obs.metrics.inc(f"query.route.{lane}")
            obs.metrics.inc("query.rows_scanned", len(batch.run_ids))
            tp = _time_plane(batch) if plan.needs_time else np.full(
                (len(batch.n_nodes), batch.v), -1, dtype=np.int32
            )
            if lane == "host":
                cap = _eval_host(batch, tp, bound, num_tables)
            else:
                cap = _eval_device(batch, tp, bound, num_tables)
            return {"cap": cap}

        return execute

    jobs = [
        Job(
            index=i,
            verb="query",
            rows=len(b.run_ids),
            v=b.v,
            e=b.e,
            work=len(b.run_ids) * (b.v + b.e),
            execute=make_execute(b),
            lanes=("sparse_device", "host"),
            source="query",
        )
        for i, b in enumerate(batches)
    ]
    outs = HeterogeneousScheduler().run(jobs, serial=serial)
    for b, o in zip(batches, outs):
        results.update(_extract(plan, b, o["cap"], vocab))
    part.per_run = results
    return part


# ---------------------------------------------------------------------------
# the corpus-level entry point
# ---------------------------------------------------------------------------


def corpus_vocab(molly):
    """One deterministic corpus-wide vocabulary: interned in run order, the
    exact order the packer itself uses — independent of cache state, so
    bound plans and name validation never depend on which segments hit.
    On the packed-first ingest path the native corpus already interned
    (bit-identically to the Python path, native/nemo_native.cpp:ingest), so
    the vocab rebuilds from its string lists — the jax_backend idiom."""
    from nemo_tpu.graphs.packed import CorpusVocab

    vocab = CorpusVocab()
    nc = getattr(molly, "native_corpus", None)
    if nc is not None:
        for t in nc.tables:
            vocab.tables.intern(t)
        for lb in nc.labels:
            vocab.labels.intern(lb)
        for tm in nc.times:
            vocab.times.intern(tm)
        return vocab
    for r in molly.runs:
        for prov in (r.pre_prov, r.post_prov):
            if prov is None:
                continue
            for g in prov.goals:
                vocab.tables.intern(g.table)
                vocab.labels.intern(g.label)
                vocab.times.intern(g.time)
            for ru in prov.rules:
                vocab.tables.intern(ru.table)
                vocab.labels.intern(ru.label)
    return vocab


def execute_query(
    q: Query,
    molly,
    *,
    result_cache: str | None = None,
    use_cache: bool = True,
    serial: bool = False,
) -> dict:
    """Plan + execute one query over an ingested corpus.  Returns the
    result document plus execution stats.

    Caching (two tiers, both content-addressed via
    ``analysis/delta.py:blob_cache_key`` so the key covers every segment
    fingerprint + the query AST hash + the analysis ABI):

    * full-result blob (namespace ``query``) — a warm repeat returns it
      with zero kernel dispatches;
    * per-segment partial blobs (namespace ``query-partial``) — a grown
      corpus maps only its NEW segments, the delta contract.
    """
    from nemo_tpu.analysis.delta import blob_cache_key, corpus_segments
    from nemo_tpu.store.rcache import resolve_result_cache

    with obs.span("query:plan", agg=q.agg, patterns=len(q.patterns)):
        plan = plan_query(q)

    seg_meta = getattr(molly, "store_segments", None)
    rc = resolve_result_cache(result_cache) if use_cache else None
    full_key = blob_cache_key("query", seg_meta, {"plan": plan.key})

    if rc is not None and full_key is not None:
        blob = rc.load_blob("query", full_key)
        if blob is not None:
            obs.metrics.inc("query.cache.hit")
            doc = json.loads(blob.decode("utf-8"))
            doc["stats"] = {"cache": "hit", "segments_mapped": 0}
            return doc
        obs.metrics.inc("query.cache.miss")

    with obs.span("query:execute", plan=plan.key[:12]):
        vocab = corpus_vocab(molly)
        plan.validate_names(vocab)
        segments = corpus_segments(molly)
        graph_of = None
        nc = getattr(molly, "native_corpus", None)
        if nc is not None:
            from nemo_tpu.graphs.packed import CorpusGraphs

            cg = CorpusGraphs(nc)
            row_by_iter = {int(it): i for i, it in enumerate(nc.iteration)}
            graph_of = lambda r: cg.get(plan.graph, row_by_iter[r.iteration])  # noqa: E731
        parts, mapped = [], 0
        for seg in segments:
            pkey = (
                blob_cache_key(
                    "query-partial",
                    [{"fingerprint": seg.fingerprint}],
                    {"plan": plan.key},
                )
                if seg.fingerprint is not None
                else None
            )
            if rc is not None and pkey is not None:
                blob = rc.load_blob("query-partial", pkey)
                if blob is not None:
                    obs.metrics.inc("query.partial.hit")
                    parts.append(QueryPartial.from_json(json.loads(blob.decode("utf-8"))))
                    continue
            obs.metrics.inc("query.partial.miss")
            part = map_segment_runs(
                plan,
                molly.runs[seg.start : seg.stop],
                vocab,
                serial=serial,
                graph_of=graph_of,
            )
            mapped += 1
            if rc is not None and pkey is not None:
                rc.put_blob(
                    "query-partial",
                    pkey,
                    json.dumps(part.to_json(), sort_keys=True).encode("utf-8"),
                )
            parts.append(part)
        doc = finalize(plan, merge_query_partials(parts))

    if rc is not None and full_key is not None:
        rc.put_blob(
            "query", full_key, json.dumps(doc, sort_keys=True).encode("utf-8")
        )
    obs.metrics.inc("query.executes")
    doc["stats"] = {
        "cache": "miss" if full_key is not None else "off",
        "segments_mapped": mapped,
    }
    return doc


# ---------------------------------------------------------------------------
# the per-run python oracle
# ---------------------------------------------------------------------------


def _oracle_holds(g, succ: dict, pred: dict, cond_tid: int) -> set:
    """Per-graph pure-Python mirror of ops/condition.py:mark_condition_holds
    (the reference both lanes' ``_condition_holds`` is measured against)."""
    goals = range(g.n_goals)
    tab = g.table_id
    roots = [n for n in goals if int(tab[n]) == cond_tid and not pred.get(n)]
    rules = {
        d
        for r in roots
        for d in succ.get(r, ())
        if d >= g.n_goals and int(tab[d]) == cond_tid
    }
    trig = {d for r in rules for d in succ.get(r, ()) if d < g.n_goals}
    if not trig:
        return set()
    trig_tables = {int(tab[t]) for t in trig if int(tab[t]) >= 0}
    return {
        n
        for n in goals
        if int(tab[n]) == cond_tid
        or (int(tab[n]) >= 0 and int(tab[n]) in trig_tables)
    }


def _oracle_eval_graph(g, bound: tuple) -> set:
    """One graph's capture set, computed with dict/set traversal — the same
    chain-intersection semantics as ``_eval_patterns`` but with none of its
    machinery (no planes, no waves, no buckets)."""
    patterns, needs_holds, cond_tid = bound
    succ: dict = {}
    pred: dict = {}
    for s, d in g.edges:
        succ.setdefault(int(s), []).append(int(d))
        pred.setdefault(int(d), []).append(int(s))
    holds = _oracle_holds(g, succ, pred, cond_tid) if needs_holds else set()
    planes = {
        "table": g.table_id, "label": g.label_id,
        "time": g.time_id, "type": g.type_id,
    }

    def passes(i: int, step: tuple) -> bool:
        for test in step:
            if test[0] == "kind":
                ok = (i < g.n_goals) if test[1] == "goal" else (i >= g.n_goals)
            elif test[0] == "holds":
                want = bool(test[2]) if test[1] == "=" else not test[2]
                ok = (i in holds) == want
            else:
                fld, op, val = test
                cur = int(planes[fld][i])
                ok = (cur == val) if op == "=" else (cur != val)
            if not ok:
                return False
        return True

    def hop(state: set, kind, fwd: bool) -> set:
        adj = succ if fwd else pred
        if kind == HOP_ADJ:
            return {d for s in state for d in adj.get(s, ())}
        reach: set = set()
        frontier = state
        while frontier:
            frontier = {d for s in frontier for d in adj.get(s, ())} - reach
            reach |= frontier
        return reach

    cap: set = set()
    for steps, hops, ci in patterns:
        masks = [{i for i in range(g.n_nodes) if passes(i, s)} for s in steps]
        fwd = [masks[0]]
        for i, h in enumerate(hops):
            fwd.append(masks[i + 1] & hop(fwd[i], h, True))
        bwd = masks[-1]
        for i in range(len(hops) - 1, ci - 1, -1):
            bwd = masks[i] & hop(bwd, hops[i], False)
        cap |= fwd[ci] & bwd
    return cap


def oracle_query(q: Query, molly) -> dict:
    """Per-run pure-Python reference evaluator: the same result document as
    :func:`execute_query`, computed one run at a time with dict/set graph
    traversal — no bucketing, no scheduler, no vectorized wave kernels, no
    caching.  The parity oracle of tests/test_query.py and the baseline the
    bench's query tier measures the batched lanes against."""
    from nemo_tpu.graphs.packed import pack_graph

    plan = plan_query(q)
    vocab = corpus_vocab(molly)
    plan.validate_names(vocab)
    bound = plan.bind(vocab)

    nc = getattr(molly, "native_corpus", None)
    if nc is not None:
        from nemo_tpu.graphs.packed import CorpusGraphs

        cg = CorpusGraphs(nc)
        row_by_iter = {int(it): i for i, it in enumerate(nc.iteration)}
        graph_of = lambda r: cg.get(plan.graph, row_by_iter[r.iteration])  # noqa: E731
    else:
        prov_of = (
            (lambda r: r.pre_prov) if plan.graph == "pre" else (lambda r: r.post_prov)
        )

        def graph_of(r):
            prov = prov_of(r)
            return None if prov is None else pack_graph(prov, vocab)

    selected = _filter_runs(molly.runs, plan.run_filter)
    part = QueryPartial(n_runs=len(selected))
    for r in selected:
        g = graph_of(r)
        if g is None or g.n_nodes == 0:
            part.per_run[r.iteration] = _empty_value(plan.agg)
            continue
        cap = _oracle_eval_graph(g, bound)
        tab = g.table_id
        if plan.agg == "tables":
            val = sorted({vocab.tables[int(tab[i])] for i in cap if int(tab[i]) >= 0})
        elif plan.agg == "count":
            val = len(cap)
        elif plan.agg == "runs":
            val = bool(cap)
        else:  # count_by_table
            hist: dict = {}
            for i in cap:
                t = int(tab[i])
                if t >= 0:
                    name = vocab.tables[t]
                    hist[name] = hist.get(name, 0) + 1
            val = hist
        part.per_run[r.iteration] = val
    doc = finalize(plan, part)
    doc["stats"] = {"cache": "oracle", "segments_mapped": 0}
    return doc


def run_query_text(text: str, molly, **kw) -> dict:
    """Text -> result document (the CLI/RPC/report-box entry point)."""
    from nemo_tpu.query.lang import parse_query

    obs.metrics.inc("query.compiles")
    q = parse_query(text)
    return execute_query(q, molly, **kw)


# Re-exported for callers that build ASTs programmatically (query/verbs.py).
__all__ = [
    "QueryError",
    "QueryPartial",
    "corpus_vocab",
    "execute_query",
    "finalize",
    "map_segment_runs",
    "merge_query_partials",
    "oracle_query",
    "run_query_text",
]
