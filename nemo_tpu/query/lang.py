"""The declarative query language: typed AST + compact text front end.

The reference exposed its provenance store through Cypher — the ten canned
analyses were just stored pattern queries (PAPER.md), and an analyst could
ask anything else.  This module reopens that generality over the packed
corpus: a query is a UNION of chain patterns over one condition's
provenance graphs, each chain a sequence of node predicates joined by
one-hop (``->``) or transitive (``-*->``) edges, with a run filter and an
order-insensitive aggregation.  ``query/plan.py`` lowers the AST onto the
existing batched CSR kernels; nothing here touches arrays.

Text form (whitespace-separated clauses, any order, ``match`` repeatable)::

    from pre
    match goal[holds=true] -> @rule[type=async] -> goal[holds=false] -> rule
    match goal[holds=false] -> @rule[type=async]
    where run.failed
    tables

* ``from pre|post`` — which condition's provenance graphs (default pre).
* ``match <chain>`` — one pattern; several ``match`` clauses union.  A step
  is ``goal``/``rule``/``node`` with an optional ``[field=value, ...]``
  predicate list (``=``/``!=``; quote values containing spaces).  Exactly
  one step per query may carry the ``@`` capture marker (default: the last
  step of each chain); matched capture nodes feed the aggregation.
* ``where run.all|run.failed|run.success`` — run filter (default all).
* aggregation — exactly one of ``tables`` (per-run sorted distinct capture
  tables + corpus distinct), ``count`` (per-run capture-node counts +
  corpus total), ``count by table`` (corpus histogram), ``runs`` (run
  iterations with >= 1 match).

Validation is LOUD (the env-knob ``policy="raise"`` precedent,
utils/env.py): unknown clause keywords, step kinds, fields, operators,
aggregations — and, at bind time, vocabulary names no corpus run ever
interned — all raise ``QueryError`` naming the junk token and the accepted
set.  A typo'd query silently matching nothing would be the analysis-layer
analog of a typo'd algorithm knob.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field

#: Bumped whenever AST canonicalization, planning, or result layout changes
#: meaning — part of every query content address (analysis/delta.py ABI
#: precedent), so stale cached results can never be served across versions.
QUERY_ABI_VERSION = 1

STEP_KINDS = ("goal", "rule", "node")
#: field -> (value domain, step kinds it applies to)
FIELDS = {
    "table": ("name", ("goal", "rule", "node")),
    "label": ("name", ("goal", "rule", "node")),
    "time": ("name", ("goal", "node")),
    "type": ("type", ("rule", "node")),
    "holds": ("bool", ("goal",)),
}
OPS = ("=", "!=")
TYPE_VALUES = ("", "async", "next", "collapsed")
GRAPHS = ("pre", "post")
RUN_FILTERS = ("all", "failed", "success")
AGGS = ("tables", "count", "count_by_table", "runs")


class QueryError(ValueError):
    """Malformed or unresolvable query — always raised loudly."""


@dataclass(frozen=True)
class Pred:
    """One node-local comparison: ``field op value``."""

    field: str
    op: str  # "=" | "!="
    value: str | bool

    def validate(self, kind: str) -> None:
        if self.field not in FIELDS:
            raise QueryError(
                f"unknown predicate field {self.field!r} "
                f"(expected one of {', '.join(FIELDS)})"
            )
        domain, kinds = FIELDS[self.field]
        if kind not in kinds:
            raise QueryError(
                f"field {self.field!r} does not apply to {kind!r} steps "
                f"(applies to: {', '.join(kinds)})"
            )
        if self.op not in OPS:
            raise QueryError(f"unknown operator {self.op!r} (expected = or !=)")
        if domain == "bool" and not isinstance(self.value, bool):
            raise QueryError(
                f"{self.field}= takes true/false, got {self.value!r}"
            )
        if domain == "type" and self.value not in TYPE_VALUES:
            raise QueryError(
                f"unknown rule type {self.value!r} "
                f"(expected one of: {', '.join(repr(t) for t in TYPE_VALUES)})"
            )


@dataclass(frozen=True)
class Step:
    """One chain position: a node-kind constraint plus predicates."""

    kind: str  # "goal" | "rule" | "node"
    preds: tuple = ()
    capture: bool = False

    def validate(self) -> None:
        if self.kind not in STEP_KINDS:
            raise QueryError(
                f"unknown step kind {self.kind!r} "
                f"(expected one of {', '.join(STEP_KINDS)})"
            )
        for p in self.preds:
            p.validate(self.kind)


#: hop kinds: one edge vs transitive closure (>= 1 hop)
HOP_ADJ, HOP_REACH = "adj", "reach"


@dataclass(frozen=True)
class Pattern:
    """A chain: steps[0] -hops[0]-> steps[1] ... (len(hops)=len(steps)-1)."""

    steps: tuple
    hops: tuple = ()

    def validate(self) -> None:
        if not self.steps:
            raise QueryError("empty pattern")
        if len(self.hops) != len(self.steps) - 1:
            raise QueryError(
                f"pattern has {len(self.steps)} steps but {len(self.hops)} hops"
            )
        for h in self.hops:
            if h not in (HOP_ADJ, HOP_REACH):
                raise QueryError(f"unknown hop {h!r} (expected -> or -*->)")
        for s in self.steps:
            s.validate()

    @property
    def capture_index(self) -> int:
        for i, s in enumerate(self.steps):
            if s.capture:
                return i
        return len(self.steps) - 1


@dataclass
class Query:
    """The full typed query: union of patterns + run filter + aggregation."""

    patterns: list = field(default_factory=list)
    graph: str = "pre"
    run_filter: str = "all"
    agg: str = "tables"

    def validate(self) -> "Query":
        if self.graph not in GRAPHS:
            raise QueryError(
                f"unknown graph {self.graph!r} (expected one of {', '.join(GRAPHS)})"
            )
        if self.run_filter not in RUN_FILTERS:
            raise QueryError(
                f"unknown run filter {self.run_filter!r} "
                f"(expected run.{' run.'.join(RUN_FILTERS)})"
            )
        if self.agg not in AGGS:
            raise QueryError(
                f"unknown aggregation {self.agg!r} "
                f"(expected one of {', '.join(AGGS)})"
            )
        if not self.patterns:
            raise QueryError("query has no match clause")
        for p in self.patterns:
            p.validate()
            if sum(1 for s in p.steps if s.capture) > 1:
                raise QueryError("at most one @capture step per pattern")
        return self

    # -- canonical form / content address ---------------------------------
    def to_json(self) -> dict:
        return {
            "graph": self.graph,
            "run_filter": self.run_filter,
            "agg": self.agg,
            "patterns": [
                {
                    "steps": [
                        {
                            "kind": s.kind,
                            "preds": [[p.field, p.op, p.value] for p in s.preds],
                            "capture": bool(s.capture),
                        }
                        for s in p.steps
                    ],
                    "hops": list(p.hops),
                }
                for p in self.patterns
            ],
        }

    def ast_hash(self) -> str:
        """Content address of the query MEANING: canonical AST + language
        ABI.  One half of every query cache key (the other half is the
        segment fingerprints, analysis/delta.py:blob_cache_key)."""
        doc = {"ast": self.to_json(), "query_abi": QUERY_ABI_VERSION}
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        ).hexdigest()


# ---------------------------------------------------------------------------
# text front end
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<arrow>-\*->|->) |
        (?P<punct>[\[\],@]) |
        (?P<quoted>"[^"]*") |
        (?P<cmp>!=|=) |
        (?P<word>[^\s\[\],=!@"]+)
    )""",
    re.VERBOSE,
)

_CLAUSE_KEYWORDS = ("from", "match", "where", "tables", "count", "runs")


def _tokenize(text: str) -> list[str]:
    toks, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise QueryError(f"cannot tokenize query at: {text[pos:pos + 20]!r}")
            break
        tok = m.group(m.lastgroup)
        if tok.strip():
            toks.append(tok)
        pos = m.end()
    return toks


class _Cursor:
    def __init__(self, toks: list[str]) -> None:
        self.toks = toks
        self.i = 0

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def take(self) -> str:
        tok = self.peek()
        if tok is None:
            raise QueryError("unexpected end of query")
        self.i += 1
        return tok

    def expect(self, tok: str) -> None:
        got = self.take()
        if got != tok:
            raise QueryError(f"expected {tok!r}, got {got!r}")


def _value(tok: str):
    if tok.startswith('"') and tok.endswith('"'):
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    return tok


def _parse_step(cur: _Cursor) -> Step:
    capture = False
    if cur.peek() == "@":
        cur.take()
        capture = True
    kind = cur.take()
    if kind not in STEP_KINDS:
        raise QueryError(
            f"unknown step kind {kind!r} (expected one of {', '.join(STEP_KINDS)})"
        )
    preds = []
    if cur.peek() == "[":
        cur.take()
        while True:
            fld = cur.take()
            op = cur.take()
            if op not in OPS:
                raise QueryError(f"expected = or != after {fld!r}, got {op!r}")
            preds.append(Pred(field=fld, op=op, value=_value(cur.take())))
            sep = cur.take()
            if sep == "]":
                break
            if sep != ",":
                raise QueryError(f"expected , or ] in predicate list, got {sep!r}")
    return Step(kind=kind, preds=tuple(preds), capture=capture)


def _parse_chain(cur: _Cursor) -> Pattern:
    steps, hops = [_parse_step(cur)], []
    while cur.peek() in ("->", "-*->"):
        hops.append(HOP_REACH if cur.take() == "-*->" else HOP_ADJ)
        steps.append(_parse_step(cur))
    return Pattern(steps=tuple(steps), hops=tuple(hops))


def parse_query(text: str) -> Query:
    """Parse the compact text form into a validated :class:`Query`."""
    cur = _Cursor(_tokenize(text))
    q = Query(patterns=[])
    seen_agg = False
    while cur.peek() is not None:
        kw = cur.take()
        if kw == "from":
            q.graph = cur.take()
        elif kw == "match":
            q.patterns.append(_parse_chain(cur))
        elif kw == "where":
            run = cur.take()
            if not run.startswith("run."):
                raise QueryError(
                    f"where takes run.all/run.failed/run.success, got {run!r}"
                )
            q.run_filter = run[len("run."):]
        elif kw in ("tables", "count", "runs"):
            if seen_agg:
                raise QueryError("more than one aggregation clause")
            seen_agg = True
            if kw == "count" and cur.peek() == "by":
                cur.take()
                by = cur.take()
                if by != "table":
                    raise QueryError(f"count by {by!r} unsupported (expected table)")
                q.agg = "count_by_table"
            else:
                q.agg = kw
        else:
            raise QueryError(
                f"unknown clause {kw!r} "
                f"(expected one of {', '.join(_CLAUSE_KEYWORDS)})"
            )
    return q.validate()
