"""Fixed analysis verbs expressed as query-layer programs.

The forcing function of the query subsystem (ISSUE 20): the pattern-shaped
analysis verbs — the reference's actual Cypher queries
(corrections.go:30-34, corrections.go:121-125, extensions.go:63-67, plus
the achieved-antecedent gate) — each have a query-layer program here whose
result is BYTE-IDENTICAL to the native verb's.  ``make query-smoke``
asserts the parity for every entry; tests/test_query.py asserts it per
lane against the per-run python oracles too.

The transform-shaped verbs (chain contraction, prototype depth ordering,
the differential frontier) are NOT pattern queries — they stay native, but
execute on the same kernels the planner lowers onto; see ARCHITECTURE.md
"The query engine".

Native-side twins: :func:`native_verb_result` computes the same-shaped
per-run map THROUGH the fixed verb path (backend kernels / host oracles),
so parity checks compare two independently-derived documents.
"""

from __future__ import annotations

from nemo_tpu.query.lang import (
    HOP_ADJ,
    Pattern,
    Pred,
    Query,
    Step,
)

_GOAL_HOLDS = Step(kind="goal", preds=(Pred("holds", "=", True),))
_GOAL_NOHOLD = Step(kind="goal", preds=(Pred("holds", "=", False),))
_RULE = Step(kind="rule")
_ASYNC = Step(kind="rule", preds=(Pred("type", "=", "async"),), capture=True)


def _chain(*steps: Step) -> Pattern:
    return Pattern(steps=tuple(steps), hops=(HOP_ADJ,) * (len(steps) - 1))


#: verb name -> the query program computing it (all validated at import).
VERB_QUERIES: dict[str, Query] = {
    # Per-run achieved-antecedent goal count — the extensions gate
    # (backend.achieved_pre_goal_counts): pre-condition goals whose
    # condition holds, table == "pre".
    "achieved_pre": Query(
        graph="pre",
        agg="count",
        patterns=[
            Pattern(
                steps=(
                    Step(
                        kind="goal",
                        preds=(
                            Pred("holds", "=", True),
                            Pred("table", "=", "pre"),
                        ),
                        capture=True,
                    ),
                )
            )
        ],
    ),
    # Pre-correction triggers (corrections.go:30-34 /
    # analysis/queries.py:find_pre_triggers): aggregation rules `a` with a
    # holding goal above and a non-holding goal below that still derives —
    # captured as the distinct trigger-rule tables per run.
    "pre_triggers": Query(
        graph="pre",
        agg="tables",
        patterns=[
            _chain(
                _GOAL_HOLDS,
                Step(kind="rule", capture=True),
                _GOAL_NOHOLD,
                _RULE,
            )
        ],
    ),
    # Post-correction triggers (corrections.go:121-125 /
    # find_post_triggers): rules below a rule-derived holding goal whose
    # own child goal fails but still derives.
    "post_triggers": Query(
        graph="post",
        agg="tables",
        patterns=[
            _chain(
                _RULE,
                _GOAL_HOLDS,
                Step(kind="rule", capture=True),
                _GOAL_NOHOLD,
                _RULE,
            )
        ],
    ),
    # Extension candidates (extensions.go:63-67 / the batched synth verb,
    # ops/sparse_{device,host}.py:synth_ext_*): async rules on the
    # antecedent's condition boundary — the union of the two reference
    # disjuncts, each a chain capturing the async rule.
    "ext_candidates": Query(
        graph="pre",
        agg="tables",
        patterns=[
            _chain(_GOAL_HOLDS, _ASYNC, _GOAL_NOHOLD, _RULE),  # cond_a
            _chain(_GOAL_NOHOLD, _ASYNC),  # cond_b
        ],
    ),
}

for _q in VERB_QUERIES.values():
    _q.validate()


def verb_query(name: str) -> Query:
    """The query program for one fixed verb (loud on unknown names)."""
    if name not in VERB_QUERIES:
        raise KeyError(
            f"unknown verb {name!r} (expected one of {', '.join(VERB_QUERIES)})"
        )
    return VERB_QUERIES[name]


def run_verb(name: str, molly, **kw) -> dict:
    """Execute one fixed verb through the query layer."""
    from nemo_tpu.query.engine import execute_query

    return execute_query(verb_query(name), molly, **kw)


def native_verb_result(name: str, backend) -> dict:
    """The NATIVE verb's per-run result, shaped like the query document's
    ``runs`` map — the byte-parity reference for :func:`run_verb`.

    The backend must have ingested its corpus (``backend.molly`` set); the
    trigger verbs walk the same kernel-holds PGraphs the corrections verb
    consumes (``backend.raw``), synth candidates ride the batched synth
    verb, achieved counts the fused achieved gate."""
    from nemo_tpu.analysis.queries import find_post_triggers, find_pre_triggers

    molly = backend.molly
    if name in ("pre_triggers", "post_triggers"):
        # The raw property-graphs mirror cond_holds from the fused kernel
        # output; load_raw_provenance wires that mirror (idempotent — the
        # fused dispatch is memoized per corpus).
        backend.load_raw_provenance()
    if name == "achieved_pre":
        return {str(k): v for k, v in backend.achieved_pre_goal_counts().items()}
    if name == "ext_candidates":
        iters = [r.iteration for r in molly.runs]
        return {str(k): v for k, v in backend.synth_candidates(iters).items()}
    if name == "pre_triggers":
        return {
            str(r.iteration): sorted(
                {t.agg.table for t in find_pre_triggers(backend.raw[(r.iteration, "pre")])}
            )
            for r in molly.runs
        }
    if name == "post_triggers":
        return {
            str(r.iteration): sorted(
                {t.rule.table for t in find_post_triggers(backend.raw[(r.iteration, "post")])}
            )
            for r in molly.runs
        }
    raise KeyError(f"unknown verb {name!r}")
