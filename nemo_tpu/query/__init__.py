"""Ad-hoc provenance query engine (ISSUE 20 / ROADMAP item 3).

The reference's real power was Cypher — arbitrary analyst questions over
the provenance store, with the canned analyses just stored queries.  This
package reopens that generality over the batched substrate: a small typed
query language (:mod:`nemo_tpu.query.lang`), a planner lowering patterns
onto the existing CSR kernel family (:mod:`nemo_tpu.query.plan`), an
executor draining per-bucket Jobs through the heterogeneous scheduler with
content-addressed result caching (:mod:`nemo_tpu.query.engine`), and the
fixed pattern verbs re-expressed as query programs
(:mod:`nemo_tpu.query.verbs`).

Surfaces: ``nemo-tpu query`` (cli.py), the JSON-carried ``Query`` sidecar
RPC (service/server.py), and the report front end's query box
(report/assets/app.js) in ``--serve``/watch mode.
"""

from __future__ import annotations

from nemo_tpu.query.engine import execute_query, oracle_query, run_query_text
from nemo_tpu.query.lang import Query, QueryError, parse_query
from nemo_tpu.query.plan import QueryPlan, plan_query

__all__ = [
    "Query",
    "QueryError",
    "QueryPlan",
    "execute_query",
    "oracle_query",
    "parse_query",
    "plan_query",
    "run_query_text",
]
