"""Bench regression sentinel: `python tools/bench_trend.py BENCH.json`.

The standing capture loop (`make bench-watch`) records BENCH jsons but
nothing READS them — a commit that regresses the hot path ships unnoticed
until someone eyeballs two captures.  This tool closes the loop:

  1. append the candidate BENCH json to a history directory
     (``--history-dir``, default ``bench_watch/history`` in the repo);
  2. compare it against the trailing **median** per metric over the most
     recent ``--window`` same-platform history entries (medians because a
     contended host makes single captures weather, and same-platform
     because a CPU-fallback capture says nothing about a TPU trend);
  3. exit nonzero past the regression threshold (default 25% relative,
     with per-unit absolute floors so sub-noise walls can't flag).

Metrics compared (direction-aware; anything missing on either side skips):

  * ``value`` (graphs/s, higher is better), ``oracle`` ratio untouched;
  * e2e tier walls (``fresh_cold``/``cached_cold``/``warm``) and the warm
    tier's per-phase walls (lower is better);
  * latency rows (``p50_diff_ms``), the giant warm wall, peak RSS;
  * **analysis route splits**: the sparse fraction of each verb's routed
    dispatches in the warm tier — a route FLIP on the same platform is
    exactly the silent regression the crossover machinery can produce, so
    any shift past the threshold (absolute) flags in either direction;
  * serving-tier p50/p99 latency, throughput, coalesce ratio and rejects
    under the standard concurrent-client load (``serve_tier``, ISSUE 8);
  * platform-profile tier (ISSUE 19): the bounded calibration wall, the
    measured-profile crossover plan's wall and its ratio to the
    hand-seeded plan, and fitted routing-constant drift vs the trailing
    same-platform medians (relative, either direction).

Accepts both raw bench result lines and the repo's ``BENCH_rNN.json``
wrapper shape (``{"parsed": {...}}``).  Entries whose result carries an
``error`` field never enter a comparison.

Exit codes: 0 ok (or insufficient history — says so), 1 regression
detected, 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (relative threshold multiplier key, absolute floor) per metric family —
#: a wall must move by both the relative threshold AND the floor to flag,
#: so timer noise on sub-second phases can't page anyone.
ABS_FLOORS = {
    "s": 0.5,  # seconds-scale walls
    "s_fast": 0.1,  # sub-second hot-path walls (warm cache hits)
    "ms": 0.05,  # millisecond latencies
    "mb": 64.0,  # RSS megabytes
    "mb_cache": 8.0,  # cache-entry sizes (a bench result cache is small)
    "ratio": 0.0,  # unitless rates/ratios: relative threshold only
}


def load_bench(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]  # the BENCH_rNN.json capture wrapper
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench result object")
    return doc


def extract_metrics(doc: dict) -> dict[str, tuple[float, str, str]]:
    """Bench doc -> {metric name: (value, direction, unit)} where direction
    is 'higher' / 'lower' / 'split' (absolute-shift comparison) / 'drift'
    (relative move vs the median in EITHER direction)."""
    out: dict[str, tuple[float, str, str]] = {}

    def put(name: str, value, direction: str, unit: str) -> None:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out[name] = (float(value), direction, unit)

    put("graphs_per_sec", doc.get("value"), "higher", "ratio")
    put("p50_diff_ms", doc.get("p50_diff_ms"), "lower", "ms")
    put("peak_rss_mb", doc.get("peak_rss_mb"), "lower", "mb")
    giant = doc.get("giant") or {}
    put("giant.warm_s", giant.get("warm_s"), "lower", "s")
    tier = doc.get("analysis_tier") or {}
    put("analysis_tier.sparse_sweep_s", tier.get("sparse_sweep_s"), "lower", "s")
    # Corpus-store ingest tier (ISSUE 5): a warm mmap load regressing toward
    # the cold parse wall, or the store bloating on disk, flags here.
    ingest = doc.get("ingest_tier") or {}
    put("ingest_tier.cold_parse_s", ingest.get("cold_parse_s"), "lower", "s")
    put("ingest_tier.warm_load_s", ingest.get("warm_load_s"), "lower", "s")
    put("ingest_tier.warm_speedup", ingest.get("warm_speedup"), "higher", "ratio")
    put("ingest_tier.store_mb", ingest.get("store_mb"), "lower", "mb")
    # Floorless companion (the 'mb' 64 MB floor was sized for RSS and would
    # mask a 3x bloat of a tens-of-MB bench store): bytes per stored run.
    if isinstance(ingest.get("store_mb"), (int, float)) and ingest.get("runs"):
        put(
            "ingest_tier.store_bytes_per_run",
            ingest["store_mb"] * 1e6 / ingest["runs"],
            "lower",
            "ratio",
        )
    # Result-cache delta tier (ISSUE 6): the warm-hit wall creeping up, the
    # grown-delta wall approaching the from-scratch wall, the cold/warm
    # speedup collapsing, or the cache entries bloating on disk all flag.
    # Sub-second-scale walls get the "s_fast" floor (0.1 s): the whole
    # POINT of the warm hit is being far under the "s" 0.5 s noise floor,
    # so the seconds-scale floor would mask a 10x regression of it.
    dtier = doc.get("delta_tier") or {}
    put("delta_tier.cold_s", dtier.get("cold_s"), "lower", "s")
    put("delta_tier.warm_hit_s", dtier.get("warm_hit_s"), "lower", "s_fast")
    put("delta_tier.grown_s", dtier.get("grown_s"), "lower", "s_fast")
    put("delta_tier.delta_speedup", dtier.get("delta_speedup"), "higher", "ratio")
    put("delta_tier.grown_fraction", dtier.get("grown_fraction"), "lower", "ratio")
    put("delta_tier.cache_mb", dtier.get("cache_mb"), "lower", "mb_cache")
    # Chaos tier (ISSUE 9): fault-tolerance cost regressions — the degraded
    # host-only wall (or the failover-path wall) creeping up against the
    # healthy wall, the crash-recovery resume approaching a from-scratch
    # rerun, or failed requests appearing under injected faults all flag.
    # failed_requests compares as an absolute shift like serve_tier.rejects
    # (an all-zero healthy history can never flag 0 -> N under relative
    # math); overhead ratios are already normalized so they carry their own
    # signal regardless of the box's absolute speed.
    chaos = doc.get("chaos_tier") or {}
    put("chaos_tier.healthy_s", chaos.get("healthy_s"), "lower", "s_fast")
    put("chaos_tier.degraded_overhead", chaos.get("degraded_overhead"), "lower", "ratio")
    put("chaos_tier.faulted_overhead", chaos.get("faulted_overhead"), "lower", "ratio")
    put("chaos_tier.recovery_overhead", chaos.get("recovery_overhead"), "lower", "ratio")
    put("chaos_tier.failed_requests", chaos.get("failed_requests"), "split", "ratio")
    # Profile tier (ISSUE 19): the calibration wall creeping up (the
    # bounded microprobe suite is only viable while it stays a few
    # seconds), the measured-profile plan's wall and its ratio to the
    # hand-seeded plan (the acceptance bar: measured no slower), and
    # fitted-constant drift vs the trailing same-platform medians — a
    # measured constant jumping on the SAME fingerprint means the
    # measurement (or the machine) changed, in either direction.
    pt = doc.get("profile_tier") or {}
    put("profile_tier.calibration_s", pt.get("calibration_s"), "lower", "s_fast")
    put("profile_tier.measured_s", pt.get("measured_s"), "lower", "s_fast")
    put(
        "profile_tier.measured_vs_seeded",
        pt.get("measured_vs_seeded"),
        "lower",
        "ratio",
    )
    for cname, cval in sorted((pt.get("constants") or {}).items()):
        put(f"profile_tier.constant.{cname}", cval, "drift", "ratio")
    # Shard tier (ISSUE 7): mesh-scaling regressions — a width's analysis
    # wall creeping up, scaling efficiency collapsing, the per-bucket
    # gather wall growing, or the scheduler's steal behavior flipping.
    # Walls get the "s_fast" floor: the whole point of an 8-way mesh is
    # being far under the seconds-scale noise floor, so sub-noise walls
    # can't flag, but a real 2x regression of a 0.5 s analysis can.
    shard = doc.get("shard_tier") or {}
    for w, row in sorted((shard.get("widths") or {}).items()):
        if isinstance(row, dict):
            put(f"shard_tier.w{w}.analysis_s", row.get("analysis_s"), "lower", "s_fast")
            put(f"shard_tier.w{w}.gather_s", row.get("gather_s"), "lower", "s_fast")
    put("shard_tier.speedup_widest", shard.get("speedup_widest"), "higher", "ratio")
    put(
        "shard_tier.scaling_efficiency_widest",
        shard.get("scaling_efficiency_widest"),
        "higher",
        "ratio",
    )
    ssched = shard.get("sched") or {}
    put("shard_tier.sched.analysis_s", ssched.get("analysis_s"), "lower", "s_fast")
    # Steal fraction comes from the CROSSOVER row (platform pin dropped —
    # the only row where both lanes and stealing can actually move; the
    # production-auto row's fraction is structurally 0 on a CPU child).
    # A steal-rate flip in EITHER direction is a scheduling change worth
    # eyes (the route-split precedent): absolute-shift compare.
    sx = shard.get("sched_crossover") or {}
    put("shard_tier.sched_crossover.analysis_s", sx.get("analysis_s"), "lower", "s_fast")
    if isinstance(sx.get("jobs"), (int, float)) and sx.get("jobs"):
        steals = float(sx.get("steal_device", 0) or 0) + float(
            sx.get("steal_host", 0) or 0
        )
        put(
            "shard_tier.sched_crossover.steal_fraction",
            steals / sx["jobs"],
            "split",
            "ratio",
        )
    # Serve tier (ISSUE 8): tail latency creeping up under the standard
    # M-concurrent-client load, throughput collapsing, the coalesce ratio
    # dropping (identical concurrent requests no longer deduped into one
    # analysis), or rejects appearing under the default queue all flag.
    # p50/p99 get the "s_fast" floor — the whole point of coalescing +
    # admission is sub-second request latency, so the seconds-scale floor
    # would mask a 5x regression of it.
    sv = doc.get("serve_tier") or {}
    put("serve_tier.p50_s", sv.get("p50_s"), "lower", "s_fast")
    put("serve_tier.p99_s", sv.get("p99_s"), "lower", "s_fast")
    put("serve_tier.throughput_rps", sv.get("throughput_rps"), "higher", "ratio")
    put("serve_tier.coalesce_ratio", sv.get("coalesce_ratio"), "higher", "ratio")
    # Rejects compare as an ABSOLUTE shift ("split"): the healthy history
    # is all-zero, where a relative compare divides by a 0 median and can
    # never flag the 0 -> N jump this metric exists to catch (any shift
    # past the threshold count flags, in either direction).
    put("serve_tier.rejects", sv.get("rejects"), "split", "ratio")
    # Fleet tier (ISSUE 14): the scale-out contract — 2-replica aggregate
    # throughput >= 1.6x one replica on the mixed-tenant warm herd with
    # p99 no worse — watched as: speedup / per-replica efficiency
    # collapsing, fleet p50/p99 (absolute, s_fast floors) or the
    # fleet-vs-single p99 ratio creeping up, the scale-out replica's warm
    # boot-to-first-response wall growing back toward compile-scale, or
    # the cold herd's cross-replica single-flight ratio collapsing (a
    # herd that stops deduping re-runs the analysis per replica).
    fl = doc.get("fleet_tier") or {}
    put("fleet_tier.speedup", fl.get("speedup"), "higher", "ratio")
    put(
        "fleet_tier.per_replica_efficiency",
        fl.get("per_replica_efficiency"),
        "higher",
        "ratio",
    )
    put("fleet_tier.p99_ratio", fl.get("p99_ratio"), "lower", "ratio")
    put("fleet_tier.fleet_p50_s", (fl.get("fleet") or {}).get("p50_s"), "lower", "s_fast")
    put("fleet_tier.fleet_p99_s", (fl.get("fleet") or {}).get("p99_s"), "lower", "s_fast")
    put(
        "fleet_tier.throughput_rps",
        (fl.get("fleet") or {}).get("throughput_rps"),
        "higher",
        "ratio",
    )
    put("fleet_tier.warm_boot_s", fl.get("warm_boot_s"), "lower", "s")
    put(
        "fleet_tier.cold_herd_dedup_ratio",
        fl.get("cold_herd_dedup_ratio"),
        "higher",
        "ratio",
    )
    # Cold-herd analyses compare as an absolute shift (the healthy value
    # is exactly 1; a 1 -> 2 jump means the fleet stopped single-flighting).
    put("fleet_tier.cold_herd_analyses", fl.get("cold_herd_analyses"), "split", "ratio")
    # Flight recorder (ISSUE 17): the armed-but-idle span cost creeping up
    # — the always-on postmortem ring buffer is only viable while its
    # hot-path tax stays a rounding error (<3% of a conservative work
    # unit, pinned by tests/test_obs_fleet.py); both the normalized
    # overhead ratio and the absolute per-span wall are watched.
    ofl = doc.get("obs_flight") or {}
    put("obs_flight.armed_idle_overhead", ofl.get("armed_idle_overhead"), "lower", "ratio")
    if isinstance(ofl.get("armed_span_us"), (int, float)):
        put("obs_flight.armed_span_ms", ofl["armed_span_us"] / 1e3, "lower", "ms")
    # Sparse-device tier (ISSUE 10): either route's wall creeping up, the
    # sparse route's watermark growing, or the giant-V watermark ratio
    # (the memory win the route exists for) collapsing all flag.  Walls
    # get the "s_fast" floor; the ratio is already normalized.
    sd = doc.get("sparse_device_tier") or {}
    for label, row in sorted(sd.items()):
        if isinstance(row, dict):
            put(
                f"sparse_device_tier.{label}.dense_wall_s",
                row.get("dense_wall_s"),
                "lower",
                "s_fast",
            )
            put(
                f"sparse_device_tier.{label}.sparse_device_wall_s",
                row.get("sparse_device_wall_s"),
                "lower",
                "s_fast",
            )
            put(
                f"sparse_device_tier.{label}.sparse_device_peak_mb",
                row.get("sparse_device_peak_mb"),
                "lower",
                "mb",
            )
    put(
        "sparse_device_tier.giant_v.watermark_ratio",
        (sd.get("giant_v") or {}).get("watermark_ratio"),
        "higher",
        "ratio",
    )
    # Stream tier (ISSUE 12): out-of-core throughput collapsing toward (or
    # past) the in-memory rate, the prefetch overlap disappearing, or the
    # streamed RSS watermark growing all flag.  peak_rss_mb additionally
    # carries a history-INDEPENDENT absolute ceiling (STREAM_RSS_CEILING_MB,
    # checked in main): the bounded-working-set contract is "under 4 GB at
    # any corpus size", not "no worse than last week".
    # Synthesis tier (ISSUE 13): the batched repair-synthesis wall creeping
    # up, its speedup over the per-run oracle collapsing (the >=5x
    # acceptance floor lives in synth-smoke; the trend watches drift), or
    # candidate throughput dropping all flag.  s_fast floors: the batched
    # walls are sub-second by design.
    sy = doc.get("synth_tier") or {}
    put("synth_tier.batched_1x_s", sy.get("batched_1x_s"), "lower", "s_fast")
    put("synth_tier.batched_full_s", sy.get("batched_full_s"), "lower", "s_fast")
    put("synth_tier.speedup_full", sy.get("speedup_full"), "higher", "ratio")
    put(
        "synth_tier.candidates_per_s",
        sy.get("candidates_per_s"),
        "higher",
        "ratio",
    )
    # Query tier (ISSUE 20): the ad-hoc query engine's three walls per
    # scale — cold plan+execute creeping up, the warm full-result rcache
    # hit regressing (s_fast floors: the acceptance bar is sub-2-second at
    # the 10k scale and the healthy value is far under it, so the
    # seconds-scale floor would mask a 10x regression), or the batched
    # engine's speedup over the per-run Python oracle collapsing.
    qt = doc.get("query_tier") or {}
    for scale in ("at_1x", "at_full"):
        row = qt.get(scale) or {}
        put(f"query_tier.{scale}.cold_s", row.get("cold_s"), "lower", "s_fast")
        put(f"query_tier.{scale}.warm_s", row.get("warm_s"), "lower", "s_fast")
        put(
            f"query_tier.{scale}.speedup_cold",
            row.get("speedup_cold"),
            "higher",
            "ratio",
        )
    st = doc.get("stream_tier") or {}
    put("stream_tier.runs_per_s", st.get("runs_per_s"), "higher", "ratio")
    put(
        "stream_tier.vs_inmemory_ratio",
        st.get("vs_inmemory_ratio"),
        "lower",
        "ratio",
    )
    put(
        "stream_tier.overlap_fraction", st.get("overlap_fraction"), "higher", "ratio"
    )
    put("stream_tier.peak_rss_mb", st.get("peak_rss_mb"), "lower", "mb")
    put("stream_tier.anon_peak_mb", st.get("anon_peak_mb"), "lower", "mb")
    put("stream_tier.rss_growth_10x", st.get("rss_growth_10x"), "lower", "ratio")
    large = st.get("large") or {}
    put("stream_tier.large.runs_per_s", large.get("runs_per_s"), "higher", "ratio")
    put("stream_tier.large.peak_rss_mb", large.get("peak_rss_mb"), "lower", "mb")
    # Watch tier (ISSUE 15): the live loop's update latency p50 (s_fast
    # floor — warm incremental cycles are sub-second), the runs/s the loop
    # absorbed, per-update dispatch count (the O(new runs) contract: a
    # jump means cached segments re-dispatched), and the steady-state RSS
    # (also bounded by an absolute ceiling in ceiling_violations).
    wt = doc.get("watch_tier") or {}
    put(
        "watch_tier.update_latency_p50_s",
        wt.get("update_latency_p50_s"),
        "lower",
        "s_fast",
    )
    put(
        "watch_tier.runs_per_s_absorbed",
        wt.get("runs_per_s_absorbed"),
        "higher",
        "ratio",
    )
    put(
        "watch_tier.dispatches_per_update",
        wt.get("dispatches_per_update"),
        "lower",
        "ratio",
    )
    # Trend on the tier-ATTRIBUTABLE growth (steady_rss_mb is the whole
    # bench child's RSS — earlier tiers' residue would flag the wrong
    # tier); the absolute number is bounded by WATCH_RSS_CEILING_MB below.
    put("watch_tier.rss_growth_mb", wt.get("rss_growth_mb"), "lower", "mb")
    # Adversarial tier (ISSUE 15): per-family walls (s_fast floors — the
    # corpora are small; what matters is a family suddenly exploding).
    for fam, row in sorted((doc.get("adversarial_tier") or {}).items()):
        put(
            f"adversarial_tier.{fam}.wall_s",
            (row or {}).get("wall_s"),
            "lower",
            "s_fast",
        )
    figures = doc.get("figures") or {}
    put(
        "figures.e2e_warm_all_figures_s",
        figures.get("e2e_warm_all_figures_s"),
        "lower",
        "s",
    )
    e2e = doc.get("e2e") or {}
    for tier_name in ("fresh_cold", "cached_cold", "warm"):
        t = e2e.get(tier_name) or {}
        put(f"e2e.{tier_name}.wall_s", t.get("wall_s"), "lower", "s")
    warm = e2e.get("warm") or {}
    for phase, v in (warm.get("phases_s") or {}).items():
        put(f"e2e.warm.phase.{phase}_s", v, "lower", "s")
    # Route splits: sparse fraction per verb of the warm tier's dispatches.
    routes = warm.get("analysis_routes") or {}
    by_verb: dict[str, dict[str, float]] = {}
    for key, n in routes.items():
        verb, _, route = key.partition(".")
        if route in ("sparse", "dense", "sparse_device"):
            by_verb.setdefault(verb, {})[route] = float(n)
    for verb, counts in by_verb.items():
        total = sum(counts.values())
        if total:
            put(
                f"route.{verb}.sparse_fraction",
                counts.get("sparse", 0.0) / total,
                "split",
                "ratio",
            )
            # The ISSUE-10 third route gets its own split signal, but only
            # once it has ever been taken — an all-dense history must not
            # grow a constant-zero metric per verb.
            if counts.get("sparse_device"):
                put(
                    f"route.{verb}.sparse_device_fraction",
                    counts["sparse_device"] / total,
                    "split",
                    "ratio",
                )
    return out


#: Absolute ceiling on the stream tier's streamed-child peak RSS (MB): the
#: ISSUE-12 single-host target is "1M runs under 4 GB", and that bound is
#: meaningful against ZERO history — a first capture over the ceiling must
#: flag even though no median exists yet.
STREAM_RSS_CEILING_MB = 4096.0

#: Absolute ceiling on the watch tier's steady-state RSS (MB): a live
#: watcher is a LONG-RUNNING process tailing a sweep for hours — its
#: memory must stay bounded regardless of how many updates it published
#: (ISSUE 15), and like the stream ceiling this is meaningful on the very
#: first capture.
WATCH_RSS_CEILING_MB = 4096.0

#: Absolute ceiling on the query tier's warm wall at the full ~10k-run
#: scale (seconds): the ISSUE-20 acceptance bar is a novel 3-pattern query
#: answered under 2 s warm — meaningful against zero history, like the RSS
#: ceilings above.
QUERY_WARM_CEILING_S = 2.0


def ceiling_violations(candidate: dict) -> list[dict]:
    """History-independent absolute bounds (the stream tier's RSS ceiling,
    default and `large` variants, plus the watch tier's steady-state RSS)."""
    out: list[dict] = []
    st = candidate.get("stream_tier") or {}
    for name, row in (("stream_tier", st), ("stream_tier.large", st.get("large") or {})):
        v = row.get("peak_rss_mb")
        if isinstance(v, (int, float)) and v > STREAM_RSS_CEILING_MB:
            out.append(
                {
                    "metric": f"{name}.peak_rss_mb",
                    "candidate": round(float(v), 1),
                    "ceiling_mb": STREAM_RSS_CEILING_MB,
                    "direction": "ceiling",
                    "regressed": True,
                }
            )
    wt = candidate.get("watch_tier") or {}
    v = wt.get("steady_rss_mb")
    if isinstance(v, (int, float)) and v > WATCH_RSS_CEILING_MB:
        out.append(
            {
                "metric": "watch_tier.steady_rss_mb",
                "candidate": round(float(v), 1),
                "ceiling_mb": WATCH_RSS_CEILING_MB,
                "direction": "ceiling",
                "regressed": True,
            }
        )
    qt = candidate.get("query_tier") or {}
    v = (qt.get("at_full") or {}).get("warm_s")
    if isinstance(v, (int, float)) and v > QUERY_WARM_CEILING_S:
        out.append(
            {
                "metric": "query_tier.at_full.warm_s",
                "candidate": round(float(v), 4),
                "ceiling_s": QUERY_WARM_CEILING_S,
                "direction": "ceiling",
                "regressed": True,
            }
        )
    return out


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def compare(
    candidate: dict, history: list[dict], threshold: float
) -> tuple[list[dict], list[dict]]:
    """Returns (regressions, verdicts) where verdicts covers every metric
    compared (regression or not) for the report."""
    cand = extract_metrics(candidate)
    hists = [extract_metrics(h) for h in history]
    regressions: list[dict] = []
    verdicts: list[dict] = []
    for name, (cv, direction, unit) in sorted(cand.items()):
        past = [h[name][0] for h in hists if name in h]
        if not past:
            continue
        med = _median(past)
        floor = ABS_FLOORS.get(unit, 0.0)
        if direction == "split":
            # Route splits are fractions in [0,1]: compare the absolute
            # shift against the threshold directly — a 25% default means a
            # quarter of the dispatches changed route.
            delta = abs(cv - med)
            bad = delta > threshold
            rel = delta
        elif direction == "drift":
            # Fitted-constant drift (profile tier): the constants span ten
            # orders of magnitude, so compare the RELATIVE move vs the
            # trailing median — and in either direction, because a measured
            # constant halving is as much a platform change as doubling.
            rel = abs(cv - med) / abs(med) if med else 0.0
            bad = rel > threshold
        elif direction == "higher":
            rel = (med - cv) / med if med else 0.0
            bad = rel > threshold
        else:  # lower is better
            rel = (cv - med) / med if med else 0.0
            bad = rel > threshold and (cv - med) > floor
        verdict = {
            "metric": name,
            "candidate": round(cv, 4),
            "trailing_median": round(med, 4),
            "samples": len(past),
            "direction": direction,
            "rel_change": round(rel, 4),
            "regressed": bool(bad),
        }
        verdicts.append(verdict)
        if bad:
            regressions.append(verdict)
    return regressions, verdicts


def _log(msg: str) -> None:
    print(msg, file=sys.stderr)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("candidate", help="BENCH json of the run under test")
    ap.add_argument(
        "--history-dir",
        default=os.path.join(REPO_ROOT, "bench_watch", "history"),
        help="directory of prior BENCH jsons (default bench_watch/history)",
    )
    ap.add_argument(
        "--baseline",
        action="append",
        default=[],
        metavar="FILE",
        help="extra history file(s) compared alongside the history dir "
        "(e.g. a pinned BENCH_rNN.json)",
    )
    ap.add_argument(
        "--threshold", type=float, default=0.25,
        help="relative regression threshold (default 0.25 = 25%%)",
    )
    ap.add_argument(
        "--window", type=int, default=5,
        help="trailing same-platform history entries per median (default 5)",
    )
    ap.add_argument(
        "--min-history", type=int, default=1,
        help="comparisons need at least this many history entries; fewer "
        "is a pass with a note (default 1)",
    )
    ap.add_argument(
        "--no-append", action="store_true",
        help="compare only; do not record the candidate into the history dir",
    )
    args = ap.parse_args(argv)

    try:
        candidate = load_bench(args.candidate)
    except (OSError, ValueError, json.JSONDecodeError) as ex:
        _log(f"bench-trend: cannot load candidate: {ex}")
        return 2
    if candidate.get("error"):
        _log(f"bench-trend: candidate carries an error field: {candidate['error']!r}; "
             "nothing to compare")
        return 2
    platform = candidate.get("platform")

    history: list[tuple[str, dict]] = []
    if os.path.isdir(args.history_dir):
        for f in sorted(os.listdir(args.history_dir)):
            if not f.endswith(".json"):
                continue
            p = os.path.join(args.history_dir, f)
            if os.path.abspath(p) == os.path.abspath(args.candidate):
                continue  # re-judging a file already in history: skip self
            try:
                history.append((p, load_bench(p)))
            except (OSError, ValueError, json.JSONDecodeError) as ex:
                _log(f"bench-trend: skipping unreadable history {p}: {ex}")
    for p in args.baseline:
        try:
            history.append((p, load_bench(p)))
        except (OSError, ValueError, json.JSONDecodeError) as ex:
            _log(f"bench-trend: skipping unreadable baseline {p}: {ex}")

    usable = [
        doc
        for _, doc in history
        if not doc.get("error") and doc.get("platform") == platform
    ]
    skipped = len(history) - len(usable)
    if skipped:
        _log(
            f"bench-trend: {skipped} history entr{'y' if skipped == 1 else 'ies'} "
            f"skipped (errored or platform != {platform!r})"
        )
    usable = usable[-args.window:]

    rc = 0
    # Absolute ceilings apply regardless of history (stream-tier RSS bound).
    ceilings = ceiling_violations(candidate)
    for c in ceilings:
        unit = "s" if "ceiling_s" in c else "MB"
        bound = c.get("ceiling_s", c.get("ceiling_mb"))
        _log(
            f"bench-trend: {c['metric']}: {c['candidate']} {unit} exceeds the "
            f"absolute ceiling {bound} {unit} [REGRESSED]"
        )
    if len(usable) < args.min_history:
        _log(
            f"bench-trend: only {len(usable)} usable same-platform history "
            f"entr{'y' if len(usable) == 1 else 'ies'} (< {args.min_history}); "
            "recording without a verdict"
        )
        verdict_doc = {"verdict": "no-history", "platform": platform}
        if ceilings:
            verdict_doc = {
                "verdict": "regression",
                "platform": platform,
                "regressions": ceilings,
            }
            rc = 1
    else:
        regressions, verdicts = compare(candidate, usable, args.threshold)
        regressions = ceilings + regressions
        for v in verdicts:
            arrow = "REGRESSED" if v["regressed"] else "ok"
            _log(
                f"bench-trend: {v['metric']}: {v['candidate']} vs trailing "
                f"median {v['trailing_median']} over {v['samples']} "
                f"({v['rel_change']:+.1%}) [{arrow}]"
            )
        verdict_doc = {
            "verdict": "regression" if regressions else "ok",
            "platform": platform,
            "threshold": args.threshold,
            "compared": len(verdicts),
            "regressions": regressions,
        }
        rc = 1 if regressions else 0

    if not args.no_append:
        os.makedirs(args.history_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
        dest = os.path.join(
            args.history_dir, f"{stamp}_{platform or 'unknown'}.json"
        )
        if os.path.abspath(args.candidate) != os.path.abspath(dest):
            shutil.copyfile(args.candidate, dest)
            verdict_doc["recorded"] = dest

    print(json.dumps(verdict_doc))
    return rc


if __name__ == "__main__":
    sys.exit(main())
