#!/usr/bin/env python3
"""Inspect and verify a ``.npack`` corpus store from the command line.

Dumps the header (format/ABI versions, source fingerprint, vocabulary
sizes), the segment manifest (runs / bucket dims / shard table with sizes
and checksums), and — by default — verifies every shard's CRC32 AND SHA-256
against the manifest, exiting nonzero on any mismatch (the integrity audit
``nemo_tpu/store`` loads only CRC-check).

Usage:
    python tools/store_inspect.py PATH [--no-verify] [--json]

PATH is either a ``.npack`` store directory (contains header.json) or a
Molly corpus directory — the latter is resolved through the corpus cache
root (``--cache`` or ``NEMO_CORPUS_CACHE``'s resolution, including its
``~/.cache/nemo_tpu/corpus`` default).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import zlib

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _resolve(path: str, cache: str | None) -> str:
    if os.path.isfile(os.path.join(path, "header.json")):
        return path
    from nemo_tpu.store import resolve_store

    store = resolve_store(cache)
    if store is None:
        raise SystemExit(
            f"{path} is not a .npack store and the corpus cache is disabled "
            "(pass --cache or unset NEMO_CORPUS_CACHE=off)"
        )
    sd = store.store_dir(path)
    if not os.path.isfile(os.path.join(sd, "header.json")):
        raise SystemExit(f"no store for corpus {path} (looked at {sd})")
    return sd


def _verify_shard(path: str, manifest: dict) -> list[str]:
    problems = []
    try:
        size = os.path.getsize(path)
    except OSError as ex:
        return [f"{manifest['file']}: unreadable ({ex})"]
    if size != int(manifest["nbytes"]):
        problems.append(
            f"{manifest['file']}: size {size} != manifest {manifest['nbytes']}"
        )
        return problems
    crc = 0
    sha = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 22)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            sha.update(chunk)
    if (crc & 0xFFFFFFFF) != int(manifest["crc32"]):
        problems.append(f"{manifest['file']}: crc32 mismatch")
    if sha.hexdigest() != manifest["sha256"]:
        problems.append(f"{manifest['file']}: sha256 mismatch")
    return problems


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="store_inspect", description=__doc__.splitlines()[0]
    )
    ap.add_argument("path", help=".npack store directory OR Molly corpus directory")
    ap.add_argument(
        "--cache",
        default=None,
        metavar="DIR",
        help="corpus cache root used to resolve a corpus-directory PATH "
        "(default: NEMO_CORPUS_CACHE resolution)",
    )
    ap.add_argument(
        "--no-verify",
        action="store_true",
        help="skip the checksum pass (header/manifest dump only)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    store_dir = _resolve(args.path, args.cache)
    with open(os.path.join(store_dir, "header.json"), "r", encoding="utf-8") as fh:
        header = json.load(fh)

    problems: list[str] = []
    shard_rows = []
    total_bytes = 0
    all_shards = [(None, header["vocab_shard"])]
    for seg in header["segments"]:
        for m in seg["shards"]:
            all_shards.append((seg["name"], m))
    for seg_name, m in all_shards:
        path = os.path.join(store_dir, *( [seg_name] if seg_name else [] ), m["file"])
        total_bytes += int(m["nbytes"])
        row = {
            "segment": seg_name,
            "file": m["file"],
            "nbytes": int(m["nbytes"]),
            "crc32": f"{int(m['crc32']):#010x}",
            "sha256": m["sha256"][:16],
            "regions": len(m["regions"]),
        }
        if not args.no_verify:
            errs = _verify_shard(path, m)
            row["ok"] = not errs
            problems += errs
        shard_rows.append(row)

    src = header.get("source", {})
    doc = {
        "store": store_dir,
        "format": header.get("format"),
        "abi": header.get("abi"),
        "source_dir": src.get("dir"),
        "n_runs": src.get("n_runs"),
        "segments": [
            {
                "name": s["name"],
                "n_runs": s["n_runs"],
                "v": s["v"],
                "e": s["e"],
                "max_depth": s["max_depth"],
                "shards": len(s["shards"]),
            }
            for s in header["segments"]
        ],
        "total_mb": round(total_bytes / 1e6, 2),
        "verified": not args.no_verify,
        "problems": problems,
    }
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(f"store:    {store_dir}")
        print(f"format:   npack v{doc['format']} / abi {doc['abi']}")
        print(f"source:   {doc['source_dir']}  ({doc['n_runs']} runs)")
        for s in doc["segments"]:
            print(
                f"segment:  {s['name']}  runs={s['n_runs']}  V={s['v']} "
                f"E={s['e']} depth={s['max_depth']}  shards={s['shards']}"
            )
        print(f"size:     {doc['total_mb']} MB across {len(shard_rows)} shards")
        for r in shard_rows:
            loc = f"{r['segment']}/{r['file']}" if r["segment"] else r["file"]
            status = "" if args.no_verify else ("  OK" if r["ok"] else "  CORRUPT")
            print(
                f"  {loc:<28} {r['nbytes']:>12} B  crc {r['crc32']}  "
                f"sha {r['sha256']}…{status}"
            )
        if problems:
            print("PROBLEMS:")
            for p in problems:
                print(f"  {p}")
        elif not args.no_verify:
            print("integrity: all checksums verified")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
