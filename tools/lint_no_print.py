"""Lint: no bare ``print(`` and no silent exception swallowing in
``nemo_tpu/`` outside the allowlists.

The library's operational output contract is structured JSON-lines logging
(nemo_tpu/obs/log.py) — leveled, machine-parseable, trace-correlated.  A
bare ``print()`` in a library layer silently reverts that contract, so this
lint (part of ``make validate``) fails the build on any real print CALL
(ast-based: string literals and comments containing "print(" never flag)
outside:

  * the CLI entry points, whose human-facing stdout IS their interface;
  * the validate/prewarm harnesses (operator-facing one-shot tools);
  * lines carrying a ``# lint: allow-print`` pragma (e.g. the log sink's
    own stderr write).

The fault-tolerance layer (ISSUE 9) extends the same discipline to error
handling: a bare ``except:`` — and an ``except Exception/BaseException:``
whose entire body is ``pass``/``...`` — silently discards failures the
robustness machinery exists to SURFACE (quarantine records, breaker
counts, degraded-mode logs), so both flag unless the ``except`` line
carries a ``# lint: allow-silent-except`` pragma stating why best-effort
swallowing is correct there (e.g. observability code that must never fail
its caller).  Handlers that log, count, re-raise, or return are fine —
only the silent-discard shape flags.

Usage: python tools/lint_no_print.py [root]   (default: repo's nemo_tpu/)
"""

from __future__ import annotations

import ast
import os
import sys

#: Paths (relative to the package root) whose stdout/stderr prints are the
#: interface: CLI entry points and operator-facing one-shot harnesses.
ALLOWLIST = {
    "cli.py",
    "dedalus/__main__.py",
    "utils/prewarm.py",
    "utils/validate_smoke.py",
}

PRAGMA = "# lint: allow-print"
EXCEPT_PRAGMA = "# lint: allow-silent-except"

#: Broad exception names whose silent-discard handlers flag; a narrow
#: ``except OSError: pass`` is a deliberate, typed decision and passes.
_BROAD_EXC = {"Exception", "BaseException"}


def _is_silent_body(body: list) -> bool:
    """True when a handler body discards the error without a trace: only
    ``pass``/``...`` statements (docstring-only bodies count too)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # '...' or a stray string literal
        return False
    return True


def check_file(path: str, rel: str) -> list[str]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as ex:
        return [f"{rel}:{ex.lineno}: unparseable: {ex.msg}"]
    lines = source.splitlines()
    problems = []

    def line_of(lineno: int) -> str:
        return lines[lineno - 1] if lineno - 1 < len(lines) else ""

    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            if PRAGMA in line_of(node.lineno):
                continue
            problems.append(
                f"{rel}:{node.lineno}: bare print() — use nemo_tpu.obs.log "
                f"(or add '{PRAGMA}' if this file IS a CLI surface)"
            )
        elif isinstance(node, ast.ExceptHandler):
            if EXCEPT_PRAGMA in line_of(node.lineno):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name) and node.type.id in _BROAD_EXC
            )
            if not broad:
                continue
            if node.type is None:
                problems.append(
                    f"{rel}:{node.lineno}: bare 'except:' — name the "
                    f"exception type (or add '{EXCEPT_PRAGMA}' with a "
                    "reason if swallowing is deliberate)"
                )
            elif _is_silent_body(node.body):
                problems.append(
                    f"{rel}:{node.lineno}: 'except {node.type.id}: pass' "
                    "swallows failures silently — log/count it via "
                    f"nemo_tpu.obs, or add '{EXCEPT_PRAGMA}' with a reason"
                )
    return problems


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "nemo_tpu"
    )
    problems: list[str] = []
    n_checked = 0
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            n_checked += 1
            problems.extend(check_file(path, rel))
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(
            f"lint-no-print: {len(problems)} bare print call(s) in "
            f"{root}", file=sys.stderr,
        )
        return 1
    print(f"lint-no-print: ok ({n_checked} files clean)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
