"""Standing device-capture tooling: `make bench-watch` (ISSUE 3 satellite).

The TPU here rides a tunnel whose outages make jax.devices() HANG, so
device bench captures keep getting deferred to "whenever the tunnel is
healthy" — and then missed (the round-5 VERDICT's capture debt).  This
watcher turns that into a fire-and-forget job:

  1. every --interval seconds, probe the device platform out-of-process
     under a hard watchdog timeout (utils/jax_config.py:
     probe_default_platform — the same probe bench.py's parent uses);
  2. on the FIRST healthy window (a non-CPU platform answered), run the
     full bench tier set (`python bench.py`, which itself re-probes and
     falls back loudly if the window closes mid-run) plus — when
     requested — the gated 10x stress row, saving the raw logs:

       <out-dir>/probe_log.txt   every probe attempt with timestamps
       <out-dir>/bench.stderr    the bench's full progress stream
       <out-dir>/BENCH.json      the single result line bench.py prints,
                                 with the host's measured platform profile
                                 stamped in (ISSUE 19 — captures are
                                 attributable to measured routing; the
                                 MULTICHIP capture gets the same stamp)

  3. run the regression sentinel (tools/bench_trend.py) over the capture:
     the result is appended to <history-dir> (default bench_watch/history)
     and compared against the trailing same-platform medians — the
     standing loop now FLAGS regressions instead of just recording them;
  4. exit 0 on a clean captured result, 2 if the sentinel flagged a
     regression, 3 if --max-wait expired with no healthy window (the
     probe log records what the tunnel did the whole time).

Run it under nohup/tmux before walking away:

    nohup make bench-watch &        # or:
    python tools/bench_watch.py --interval 300 --max-wait 86400 --with-10x
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _stamp() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _profile_stamp(plog, timeout: float = 180.0):
    """The host's persistent measured platform profile (the ~/.cache
    root), fetched OUT-OF-PROCESS like every other device touch here (a
    tunnel hang must stall a subprocess, not the watcher): calibrates once
    on the first healthy capture, every later capture loads with zero
    probes.  Stamped into the BENCH/MULTICHIP capture documents (ISSUE
    19) so the recorded numbers are attributable to measured — not
    hand-seeded — routing.  Best effort: never fails the capture."""
    code = (
        "import json\n"
        "from nemo_tpu.platform import profile as pp\n"
        "pp.ensure_calibrated()\n"
        "print(json.dumps(pp.telemetry_section()))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            timeout=timeout,
            cwd=REPO_ROOT,
        )
        line = next(
            ln for ln in reversed((proc.stdout or "").strip().splitlines())
            if ln.startswith("{")
        )
        sect = json.loads(line)
        plog(
            "platform profile stamp: "
            f"mode={sect.get('mode')} key={sect.get('key', '<seeded>')}"
        )
        return sect
    except Exception as ex:
        plog(f"platform profile stamp skipped: {type(ex).__name__}: {ex}")
        return None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between device probes (default 300)")
    ap.add_argument("--probe-timeout", type=float, default=60.0,
                    help="watchdog seconds per probe attempt (default 60)")
    ap.add_argument("--max-wait", type=float, default=24 * 3600.0,
                    help="give up after this many seconds (default 1 day)")
    ap.add_argument("--out-dir", default=None,
                    help="log/result directory (default bench_watch/<UTC stamp>)")
    ap.add_argument("--with-10x", action="store_true",
                    help="also capture the gated 10x stress row (NEMO_BENCH_10X=1)")
    ap.add_argument("--runs", type=int, default=None,
                    help="override NEMO_BENCH_RUNS for the capture")
    ap.add_argument("--once", action="store_true",
                    help="probe exactly once, then run or exit 3 (for tests/cron)")
    ap.add_argument("--history-dir", default=None,
                    help="bench_trend history directory (default "
                    "bench_watch/history); 'off' skips the sentinel")
    ap.add_argument("--trend-threshold", type=float, default=0.25,
                    help="bench_trend relative regression threshold (default 0.25)")
    args = ap.parse_args()

    out_dir = args.out_dir or os.path.join(
        REPO_ROOT, "bench_watch",
        datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%d_%H%M%S"),
    )
    os.makedirs(out_dir, exist_ok=True)
    probe_log_path = os.path.join(out_dir, "probe_log.txt")

    from nemo_tpu.utils.jax_config import probe_default_platform

    def plog(msg: str) -> None:
        line = f"[{_stamp()}] {msg}"
        print(line, file=sys.stderr, flush=True)
        with open(probe_log_path, "a", encoding="utf-8") as fh:
            fh.write(line + "\n")

    plog(f"bench-watch started; logs in {out_dir}")
    deadline = time.monotonic() + args.max_wait
    healthy = None
    while True:
        info = probe_default_platform(args.probe_timeout, retries=1, log=plog)
        if info is not None and info.get("platform") != "cpu":
            healthy = info
            plog(f"healthy window: {info['platform']} x{info['n']}")
            break
        plog(
            "no healthy device window "
            f"({'cpu-only' if info else 'probe timed out'}); "
            f"next probe in {args.interval:.0f}s"
        )
        if args.once or time.monotonic() + args.interval > deadline:
            plog("max wait exceeded; giving up (exit 3)")
            return 3
        time.sleep(args.interval)

    # Healthy window: run the full bench tier set, raw logs preserved.
    env = dict(os.environ)
    if args.with_10x:
        env["NEMO_BENCH_10X"] = "1"
    if args.runs is not None:
        env["NEMO_BENCH_RUNS"] = str(args.runs)
    stderr_path = os.path.join(out_dir, "bench.stderr")
    plog(f"running bench tier set (stderr -> {stderr_path})")
    with open(stderr_path, "w", encoding="utf-8") as err_fh:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
            stdout=subprocess.PIPE,
            stderr=err_fh,
            text=True,
            env=env,
            cwd=REPO_ROOT,
        )
    lines = (proc.stdout or "").strip().splitlines()
    result_path = os.path.join(out_dir, "BENCH.json")
    if not lines:
        plog(f"bench produced no result line (rc={proc.returncode}); see {stderr_path}")
        return 1
    # The measured-profile attribution stamped into every capture document
    # written below (BENCH + MULTICHIP) — fetched once per capture.
    profile_sect = _profile_stamp(plog)
    try:
        result = json.loads(lines[-1])
        if profile_sect is not None and isinstance(result, dict):
            result["platform_profile"] = profile_sect
        summary = {
            k: result.get(k)
            for k in ("platform", "value", "vs_baseline", "error")
            if result.get(k) is not None
        }
    except json.JSONDecodeError:
        result = None
        summary = {"error": "unparseable result line"}
    with open(result_path, "w", encoding="utf-8") as fh:
        fh.write(
            (json.dumps(result) if isinstance(result, dict) else lines[-1]) + "\n"
        )
    plog(
        f"captured (rc={proc.returncode}, probed {healthy['platform']}): "
        f"{json.dumps(summary)} -> {result_path}"
    )
    if proc.returncode != 0 or "error" in summary:
        return 1

    # MULTICHIP refresh (ISSUE 7): when the healthy window exposes a real
    # multi-device mesh, measure REAL mesh scaling on it — the shard tier's
    # measurement child pointed at the device platform instead of virtual
    # CPU devices — and save it as MULTICHIP.json next to the BENCH capture.
    # Best effort: a scaling capture must never fail the bench capture.
    if int(healthy.get("n", 1)) > 1:
        mc_path = os.path.join(out_dir, "MULTICHIP.json")
        plog(f"multi-device window ({healthy['n']} chips): capturing mesh scaling")
        mc_env = dict(os.environ)
        # Leave the platform selection alone — the tunnel device is only
        # reachable through the default selection (bench.py child notes).
        mc_env["NEMO_BENCH_SHARD_PLATFORM"] = "auto"
        try:
            mc_proc = subprocess.run(
                [sys.executable, os.path.join(REPO_ROOT, "bench.py"), "--shard-child"],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                timeout=1800,
                env=mc_env,
                cwd=REPO_ROOT,
            )
            mc_lines = (mc_proc.stdout or "").strip().splitlines()
            json_line = next(
                (ln for ln in reversed(mc_lines) if ln.startswith("{")), None
            )
            if json_line and profile_sect is not None:
                try:
                    mc_doc = json.loads(json_line)
                    if isinstance(mc_doc, dict):
                        mc_doc["platform_profile"] = profile_sect
                        json_line = json.dumps(mc_doc)
                except json.JSONDecodeError:
                    pass
            with open(mc_path, "w", encoding="utf-8") as fh:
                if json_line:
                    fh.write(json_line + "\n")
                else:
                    json.dump({"rc": mc_proc.returncode, "ok": False,
                               "tail": "\n".join(mc_lines[-5:])}, fh)
            plog(f"mesh scaling capture (rc={mc_proc.returncode}) -> {mc_path}")
        except Exception as ex:
            plog(f"mesh scaling capture skipped: {type(ex).__name__}: {ex}")

    # Regression sentinel: append this capture to the trailing history and
    # compare against the per-metric medians; a flagged regression turns
    # the watcher's exit code to 2 so the cron/tmux wrapper can page.
    if args.history_dir == "off":
        return 0
    history_dir = args.history_dir or os.path.join(REPO_ROOT, "bench_watch", "history")
    trend = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "tools", "bench_trend.py"),
            result_path,
            "--history-dir", history_dir,
            "--threshold", str(args.trend_threshold),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
    )
    tail = (trend.stdout or "").strip().splitlines()
    plog(f"bench-trend (rc={trend.returncode}): {tail[-1] if tail else '<no output>'}")
    with open(os.path.join(out_dir, "trend.txt"), "w", encoding="utf-8") as fh:
        fh.write(trend.stdout or "")
    if trend.returncode == 1:
        return 2  # regression flagged
    # A sentinel usage/input error must not read as "no regression".
    return 0 if trend.returncode == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
