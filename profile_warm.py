"""Profiling harness for the warm e2e path (not shipped; dev tool).

Generates one bench-scale family corpus, runs run_debug once to warm the jit
caches, then cProfiles a second run_debug and prints phase timings plus the
top cumulative-time entries.
"""

import cProfile
import io
import os
import pstats
import sys
import tempfile
import time

# Resolve the platform BEFORE jax init like every entry point (the
# environment's tunnel plugin force-registers itself; during an outage its
# client-init hangs/dies even for CPU-intended runs).  ensure_platform()
# honors the repo-wide NEMO_PLATFORM convention: cpu pins immediately,
# tpu demands the device via the watchdog probe, unset probes and falls
# back to CPU loudly.
from nemo_tpu.utils.jax_config import ensure_platform

ensure_platform()

from nemo_tpu.analysis.pipeline import run_debug
from nemo_tpu.backend.jax_backend import JaxBackend
from nemo_tpu.models.case_studies import write_case_study
from nemo_tpu.utils.jax_config import enable_compilation_cache

enable_compilation_cache()

family = os.environ.get("FAMILY", "CA-2083-hinted-handoff")
n_runs = int(os.environ.get("RUNS", "1700"))
tmp = tempfile.mkdtemp(prefix="nemo_prof_")
t0 = time.perf_counter()
d = write_case_study(family, n_runs=n_runs, seed=11, out_dir=os.path.join(tmp, "big"))
print(f"gen: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

res = run_debug(d, os.path.join(tmp, "r1"), JaxBackend(), figures="sample:8")
print("cold phases:", {k: round(v, 2) for k, v in res.timings.items()}, file=sys.stderr)

pr = cProfile.Profile()
for i in range(3):
    t0 = time.perf_counter()
    if i == 2:
        pr.enable()
    res = run_debug(d, os.path.join(tmp, f"r2_{i}"), JaxBackend(), figures="sample:8")
    if i == 2:
        pr.disable()
    wall = time.perf_counter() - t0
    print(f"warm wall [{i}]: {wall:.2f}s", file=sys.stderr)
    print(f"warm phases [{i}]:", {k: round(v, 2) for k, v in res.timings.items()}, file=sys.stderr)

s = io.StringIO()
ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
ps.print_stats(45)
print(s.getvalue())
