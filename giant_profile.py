"""Giant-path TPU profile (dev tool; VERDICT r3 task 7).

Runs the 10k-node scenario on the real device twice (cold incl. compile,
then warm) against the Python oracle, with phase timings."""

import sys
import tempfile
import time

from nemo_tpu.utils.jax_config import enable_compilation_cache, ensure_platform

platform = ensure_platform(None)
print("platform:", platform, file=sys.stderr)
enable_compilation_cache()

import os

from nemo_tpu.analysis.pipeline import run_debug
from nemo_tpu.backend.jax_backend import JaxBackend
from nemo_tpu.backend.python_ref import PythonBackend
from nemo_tpu.models.synth import GIANT10K_THRESHOLD_V, giant10k_spec, write_corpus

os.environ["NEMO_GIANT_V"] = str(GIANT10K_THRESHOLD_V)

tmp = tempfile.mkdtemp(prefix="nemo_giant_")
corpus = write_corpus(giant10k_spec(), tmp)

for label in ("cold", "warm"):
    t0 = time.perf_counter()
    jx = run_debug(corpus, f"{tmp}/jx_{label}", JaxBackend(), figures="none")
    wall = time.perf_counter() - t0
    print(f"giant [{label}]: {wall:.1f}s", {k: round(v, 2) for k, v in jx.timings.items()})

t0 = time.perf_counter()
py = run_debug(corpus, f"{tmp}/py", PythonBackend(), figures="none")
t_py = time.perf_counter() - t0
print(f"oracle: {t_py:.1f}s", {k: round(v, 2) for k, v in py.timings.items()})

import json

a = json.load(open(f"{tmp}/jx_warm/giant10k/debugging.json"))
b = json.load(open(f"{tmp}/py/giant10k/debugging.json"))
print("identical:", a == b)
